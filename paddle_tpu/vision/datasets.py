"""paddle.vision.datasets equivalent (reference:
python/paddle/vision/datasets/ — MNIST/FashionMNIST (mnist.py), Cifar10/
Cifar100 (cifar.py), Flowers (flowers.py), DatasetFolder/ImageFolder
(folder.py), VOC2012 (voc2012.py)).

No network in this environment: every dataset takes the same archive files
the reference downloads (image_path/label_path/data_file) and parses them
identically; constructing without the files raises with the expected
layout."""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.io import Dataset

__all__ = [
    "MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers",
    "DatasetFolder", "ImageFolder", "VOC2012",
]


def _require(path, name, what):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"{name} requires a local copy (no network): pass {what}"
        )


class MNIST(Dataset):
    """reference vision/datasets/mnist.py:27 — idx-format image/label
    files (optionally .gz)."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        _require(image_path, self.NAME, "image_path (idx3-ubyte[.gz])")
        _require(label_path, self.NAME, "label_path (idx1-ubyte[.gz])")
        self.mode = mode
        self.transform = transform
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            if magic != 2051:
                raise ValueError(f"bad idx3 magic {magic}")
            data = np.frombuffer(f.read(n * rows * cols), np.uint8)
        return data.reshape(n, rows, cols).astype(np.float32)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            if magic != 2049:
                raise ValueError(f"bad idx1 magic {magic}")
            return np.frombuffer(f.read(n), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    """reference vision/datasets/mnist.py FashionMNIST — same idx format."""

    NAME = "FashionMNIST"


class Cifar10(Dataset):
    """reference vision/datasets/cifar.py:29 — python-pickle batch archive
    (cifar-10-python.tar.gz)."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        _require(data_file, type(self).__name__, "data_file (the python-version tar.gz)")
        self.transform = transform
        wanted = self._train_members if mode == "train" else self._test_members
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                base = os.path.basename(member.name)
                if base in wanted:
                    batch = pickle.loads(tf.extractfile(member).read(), encoding="bytes")
                    images.append(batch[b"data"])
                    labels.extend(batch[self._label_key])
        data = np.concatenate(images).reshape(-1, 3, 32, 32).astype(np.float32)
        self.images = data
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    """reference vision/datasets/cifar.py Cifar100."""

    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"


class Flowers(Dataset):
    """reference vision/datasets/flowers.py:33 — 102flowers images +
    imagelabels.mat + setid.mat."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        _require(data_file, "Flowers", "data_file (102flowers.tgz)")
        _require(label_file, "Flowers", "label_file (imagelabels.mat)")
        _require(setid_file, "Flowers", "setid_file (setid.mat)")
        import scipy.io as sio

        self.transform = transform
        labels = sio.loadmat(label_file)["labels"][0]
        setid = sio.loadmat(setid_file)
        key = {"train": "trnid", "valid": "valid", "test": "tstid"}[mode]
        self.indexes = setid[key][0]
        self.labels = labels
        # keep one open handle: gzip tars have no random access, so
        # reopening per item would decompress half the archive each time
        self._tar = tarfile.open(data_file)
        self._members = {
            os.path.basename(m.name): m.name
            for m in self._tar.getmembers()
            if m.name.endswith(".jpg")
        }

    def __getitem__(self, idx):
        flower_id = int(self.indexes[idx])
        name = f"image_{flower_id:05d}.jpg"
        raw = self._tar.extractfile(self._members[name]).read()
        img = _decode_image(raw)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(self.labels[flower_id - 1] - 1, np.int64)

    def __len__(self):
        return len(self.indexes)


def _decode_image(raw):
    try:
        from PIL import Image
        import io

        return np.asarray(Image.open(io.BytesIO(raw)).convert("RGB"))
    except ImportError:
        raise RuntimeError("image decoding requires Pillow") from None


_IMG_EXTS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif", ".tiff", ".webp", ".npy")


def _walk_files(root, extensions, is_valid_file):
    for dirpath, _, files in sorted(os.walk(root)):
        for fn in sorted(files):
            path = os.path.join(dirpath, fn)
            ok = is_valid_file(path) if is_valid_file else fn.lower().endswith(extensions)
            if ok:
                yield path


class DatasetFolder(Dataset):
    """class-per-subdirectory image tree (reference
    vision/datasets/folder.py:60)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        _require(root, "DatasetFolder", "root directory")
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = tuple(extensions) if extensions else _IMG_EXTS
        classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        )
        if not classes:
            raise RuntimeError(f"no class folders under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            for path in _walk_files(os.path.join(root, c), extensions, is_valid_file):
                self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        with open(path, "rb") as f:
            return _decode_image(f.read())

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(target, np.int64)

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """flat/unlabelled image tree (reference vision/datasets/folder.py:253)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        _require(root, "ImageFolder", "root directory")
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        extensions = tuple(extensions) if extensions else _IMG_EXTS
        self.samples = list(_walk_files(root, extensions, is_valid_file))
        if not self.samples:
            raise RuntimeError(f"no valid files under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class VOC2012(Dataset):
    """reference vision/datasets/voc2012.py:28 — segmentation pairs from
    the VOCtrainval tar."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        _require(data_file, "VOC2012", "data_file (VOCtrainval_11-May-2012.tar)")
        self.transform = transform
        base = "VOCdevkit/VOC2012"
        # reference voc2012.py split map: train->trainval, valid->val,
        # test->train (VOC's real test set is not in the trainval archive)
        split = {"train": "trainval", "valid": "val", "test": "train"}[mode]
        self._tar = tarfile.open(data_file)
        lst = self._tar.extractfile(f"{base}/ImageSets/Segmentation/{split}.txt").read().decode()
        self.names = [n.strip() for n in lst.splitlines() if n.strip()]
        self._base = base

    def __getitem__(self, idx):
        name = self.names[idx]
        img = _decode_image(self._tar.extractfile(f"{self._base}/JPEGImages/{name}.jpg").read())
        lbl = _decode_image(self._tar.extractfile(f"{self._base}/SegmentationClass/{name}.png").read())
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl

    def __len__(self):
        return len(self.names)
