"""PP-OCR-class text recognizer (BASELINE.md row: PP-OCRv4).

Reference lineage: the PP-OCR recognition pipeline served from the
reference's vision/text stack — a conv feature extractor squeezed to a
sequence, a bidirectional LSTM encoder, and a CTC head trained with
`ctc_loss` (python/paddle/nn/functional/loss.py warpctc lineage;
paddle/phi/kernels/gpu/warpctc_kernel.cu).

TPU-native notes: static [B, 3, 32, W] inputs, the height axis fully
collapsed by stride-(2,1) convs so the sequence length is W/4 at trace
time (no dynamic shapes), the BiLSTM is the framework's lax.scan-based
nn.LSTM, and greedy CTC decode is a jit-friendly argmax + host-side
collapse.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["CRNN", "ppocr_rec_tiny", "ctc_greedy_decode"]


from .detection import ConvBNLayer


def _ConvBlock(cin, cout, stride):
    return ConvBNLayer(cin, cout, k=3, stride=stride, act="relu")


class CRNN(nn.Layer):
    """conv stack -> [B, T, C] sequence -> BiLSTM -> CTC logits.

    forward(x[B, 3, 32, W]) -> log-probs [B, T=W/4, num_classes+1]
    (class 0 is the CTC blank, matching nn.functional.ctc_loss)."""

    def __init__(self, num_classes=96, hidden=64, widths=(32, 64, 128)):
        super().__init__()
        w = list(widths)
        self.convs = nn.Sequential(
            _ConvBlock(3, w[0], stride=2),          # 32 -> 16, W -> W/2
            _ConvBlock(w[0], w[1], stride=(2, 2)),  # 16 -> 8,  W/2 -> W/4
            _ConvBlock(w[1], w[2], stride=(2, 1)),  # 8 -> 4,   keep W/4
            _ConvBlock(w[2], w[2], stride=(4, 1)),  # 4 -> 1,   keep W/4
        )
        self.rnn = nn.LSTM(w[2], hidden, direction="bidirect")
        self.head = nn.Linear(2 * hidden, num_classes + 1)
        self.num_classes = num_classes

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        h = self.convs(x)                      # [B, C, 1, T]
        h = h.squeeze(2).transpose([0, 2, 1])  # [B, T, C]
        h, _ = self.rnn(h)
        logits = self.head(h)                  # [B, T, K+1]
        return F.log_softmax(logits, axis=-1)

    def loss(self, log_probs, labels, label_lengths):
        """CTC loss over the full (static) time axis."""
        import paddle_tpu.nn.functional as F

        B, T = log_probs.shape[0], log_probs.shape[1]
        input_lengths = paddle.full([B], T, dtype="int64")
        return F.ctc_loss(log_probs.transpose([1, 0, 2]), labels,
                          input_lengths, label_lengths, blank=0)


def ctc_greedy_decode(log_probs, blank=0):
    """[B, T, K] log-probs -> list of decoded id lists (collapse repeats,
    drop blanks) — host-side, like the reference's ctc_align op."""
    ids = np.asarray(paddle.argmax(log_probs, axis=-1)._value)
    out = []
    for row in ids:
        seq, prev = [], blank
        for t in row:
            t = int(t)
            if t != blank and t != prev:
                seq.append(t)
            prev = t
        out.append(seq)
    return out


def ppocr_rec_tiny(num_classes=96, **kw):
    return CRNN(num_classes=num_classes, hidden=48, widths=(16, 32, 64), **kw)
