"""MobileNet V1/V2/V3 (reference:
python/paddle/vision/models/{mobilenetv1,mobilenetv2,mobilenetv3}.py —
standard depthwise-separable architectures on this framework's nn layers).
Depthwise convs use Conv2D(groups=channels), which XLA lowers to TPU
feature-group convolutions."""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = [
    "MobileNetV1", "mobilenet_v1",
    "MobileNetV2", "mobilenet_v2",
    "MobileNetV3Small", "MobileNetV3Large",
    "mobilenet_v3_small", "mobilenet_v3_large",
]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act=nn.ReLU):
        pad = (k - 1) // 2
        layers = [
            nn.Conv2D(in_c, out_c, k, stride=stride, padding=pad, groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


class MobileNetV1(nn.Layer):
    """reference vision/models/mobilenetv1.py:80"""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return int(ch * scale)

        cfg = [  # (out, stride) of each depthwise-separable block
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
            (1024, 2), (1024, 1),
        ]
        layers = [_ConvBNReLU(3, c(32), 3, stride=2)]
        in_c = c(32)
        for out, s in cfg:
            layers.append(_ConvBNReLU(in_c, in_c, 3, stride=s, groups=in_c))  # dw
            layers.append(_ConvBNReLU(in_c, c(out), 1))  # pw
            in_c = c(out)
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV1(scale=scale, **kwargs)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        hidden = int(round(in_c * expand_ratio))
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNReLU(in_c, hidden, 1, act=nn.ReLU6))
        layers += [
            _ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden, act=nn.ReLU6),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """reference vision/models/mobilenetv2.py:38"""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, act=nn.ReLU6)]
        for t, c_, n, s in cfg:
            out_c = _make_divisible(c_ * scale)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNReLU(in_c, last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV2(scale=scale, **kwargs)


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze_factor=4):
        super().__init__()
        sq = _make_divisible(ch // squeeze_factor)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, sq, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(sq, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _V3Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(_ConvBNReLU(in_c, exp, 1, act=act))
        layers.append(_ConvBNReLU(exp, exp, k, stride=stride, groups=exp, act=act))
        if se:
            layers.append(_SqueezeExcite(exp))
        layers += [nn.Conv2D(exp, out_c, 1, bias_attr=False), nn.BatchNorm2D(out_c)]
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_LARGE = [  # k, exp, out, se, act, stride
    (3, 16, 16, False, nn.ReLU, 1), (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1), (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1), (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2), (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1), (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1), (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2), (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1),
]
_V3_SMALL = [
    (3, 16, 16, True, nn.ReLU, 2), (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1), (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1), (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1), (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2), (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1),
]


class _MobileNetV3(nn.Layer):
    """reference vision/models/mobilenetv3.py MobileNetV3 base."""

    def __init__(self, cfg, last_exp, last_c, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return _make_divisible(ch * scale)

        in_c = c(16)
        layers = [_ConvBNReLU(3, in_c, 3, stride=2, act=nn.Hardswish)]
        for k, exp, out, se, act, s in cfg:
            layers.append(_V3Block(in_c, c(exp), c(out), k, s, se, act))
            in_c = c(out)
        layers.append(_ConvBNReLU(in_c, c(last_exp), 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(c(last_exp), last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes),
            )

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, 1280, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, 1024, scale, num_classes, with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV3Large(scale=scale, **kwargs)
