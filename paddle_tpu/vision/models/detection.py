"""PP-YOLOE-class single-stage detector (BASELINE.md row: PP-YOLOE).

Reference lineage: the PP-YOLO family served from the reference's vision
stack — CSP backbone blocks + FPN neck + per-level heads decoded by the
`yolo_box` operator (python/paddle/vision/ops.py yolo_box; CUDA kernel
paddle/phi/kernels/gpu/yolo_box_kernel.cu).

TPU-native design notes: everything is static-shaped dense conv compute
(MXU-friendly NCHW convs XLA lays out itself); the decode is the already-
verified `paddle_tpu.vision.ops.yolo_box` running inside the same jit —
no dynamic-shape NMS in the compiled path (candidate filtering is a
host-side post-step, like the reference's multiclass_nms living outside
the TensorRT-compiled subgraph).
"""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

__all__ = ["PPYoloDet", "ppyolo_tiny", "ppyolo_s"]


class ConvBNLayer(nn.Layer):
    """conv + BN + activation (shared by the detection and OCR families)."""

    def __init__(self, cin, cout, k=3, stride=1, groups=1, act="silu"):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(cout)
        self.act = {"silu": nn.Silu, "relu": nn.ReLU}[act]()

    def forward(self, x):
        return self.act(self.bn(self.conv(x)))


class CSPResBlock(nn.Layer):
    """CSP residual block: split, residual-conv half, concat, fuse."""

    def __init__(self, ch, n=1):
        super().__init__()
        half = ch // 2
        self.left = ConvBNLayer(ch, half, k=1)
        self.right = ConvBNLayer(ch, half, k=1)
        self.blocks = nn.LayerList([
            nn.Sequential(ConvBNLayer(half, half, 1), ConvBNLayer(half, half, 3))
            for _ in range(n)
        ])
        self.fuse = ConvBNLayer(ch, ch, k=1)

    def forward(self, x):
        left = self.left(x)
        right = self.right(x)
        for blk in self.blocks:
            right = right + blk(right)
        return self.fuse(paddle.concat([left, right], axis=1))


class PPYoloDet(nn.Layer):
    """Backbone (stem + CSP stages) -> top-down FPN -> per-level anchor
    heads.  forward(x) returns per-level raw head maps
    [B, A*(5+C), H, W] for training; `decode(outputs, img_size)` runs
    yolo_box per level and concatenates boxes/scores."""

    def __init__(self, num_classes=80, widths=(32, 64, 128, 256, 256),
                 depth=1, anchors=None, downsample_ratios=(8, 16, 32)):
        super().__init__()
        self.num_classes = num_classes
        # one anchor set per FPN level (PP-YOLO tiny defaults, px)
        self.anchors = anchors or [
            [10, 15, 24, 36, 72, 42],
            [35, 87, 102, 96, 60, 170],
            [220, 125, 128, 222, 264, 266],
        ]
        self.downsample_ratios = list(downsample_ratios)

        w = list(widths)
        self.stem = ConvBNLayer(3, w[0], 3, stride=2)
        stages = []
        for i in range(1, len(w)):
            stages.append(nn.Sequential(
                ConvBNLayer(w[i - 1], w[i], 3, stride=2),
                CSPResBlock(w[i], n=depth),
            ))
        self.stages = nn.LayerList(stages)

        # top-down neck over the last 3 stages
        c3, c4, c5 = w[-3], w[-2], w[-1]
        self.lat5 = ConvBNLayer(c5, c4, 1)
        self.lat4 = ConvBNLayer(c4 + c4, c3, 1)
        self.lat3 = ConvBNLayer(c3 + c3, c3, 1)
        self.up = nn.Upsample(scale_factor=2, mode="nearest")

        per_anchor = len(self.anchors[0]) // 2
        out_ch = per_anchor * (5 + num_classes)
        self.heads = nn.LayerList([
            nn.Conv2D(c, out_ch, 1) for c in (c3, c3, c4)
        ])

    def forward(self, x):
        feats = []
        h = self.stem(x)
        for st in self.stages:
            h = st(h)
            feats.append(h)
        c3, c4, c5 = feats[-3], feats[-2], feats[-1]
        p5 = self.lat5(c5)                                  # [B, c4, H/32]
        p4 = self.lat4(paddle.concat([self.up(p5), c4], 1))  # [B, c3, H/16]
        p3 = self.lat3(paddle.concat([self.up(p4), c3], 1))  # [B, c3, H/8]
        return [self.heads[0](p3), self.heads[1](p4), self.heads[2](p5)]

    def decode(self, outputs, img_size, conf_thresh=0.01):
        """Per-level yolo_box decode -> (boxes [B, N, 4], scores [B, N, C])."""
        from paddle_tpu.vision import ops as V

        boxes, scores = [], []
        imgsz = paddle.to_tensor(
            [[int(img_size), int(img_size)]] * outputs[0].shape[0], dtype="int32"
        )
        for out, anchors, ds in zip(outputs, self.anchors,
                                    self.downsample_ratios):
            b, s = V.yolo_box(out, imgsz, anchors, self.num_classes,
                              conf_thresh, ds)
            boxes.append(b)
            scores.append(s)
        return paddle.concat(boxes, axis=1), paddle.concat(scores, axis=1)


def ppyolo_tiny(num_classes=80, **kw):
    return PPYoloDet(num_classes, widths=(16, 32, 64, 128, 128), depth=1, **kw)


def ppyolo_s(num_classes=80, **kw):
    return PPYoloDet(num_classes, widths=(32, 64, 128, 256, 256), depth=2, **kw)
