"""DenseNet, GoogLeNet, InceptionV3, ShuffleNetV2 (reference:
python/paddle/vision/models/{densenet,googlenet,inceptionv3,
shufflenetv2}.py — standard architectures on this framework's nn layers)."""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn

from .mobilenet import _ConvBNReLU as _ConvBNAct

__all__ = [
    "DenseNet", "densenet121", "densenet161", "densenet169", "densenet201", "densenet264",
    "GoogLeNet", "googlenet",
    "InceptionV3", "inception_v3",
    "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
    "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
    "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]


# DenseNet ------------------------------------------------------------------

class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return paddle.concat([x, out], axis=1)


class _Transition(nn.Sequential):
    def __init__(self, in_c, out_c):
        super().__init__(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False), nn.AvgPool2D(2, 2),
        )


_DENSE_CFG = {
    121: (64, 32, [6, 12, 24, 16]),
    161: (96, 48, [6, 12, 36, 24]),
    169: (64, 32, [6, 12, 32, 32]),
    201: (64, 32, [6, 12, 48, 32]),
    264: (64, 32, [6, 12, 64, 48]),
}


class DenseNet(nn.Layer):
    """reference vision/models/densenet.py:300"""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000, with_pool=True):
        super().__init__()
        init_c, growth, blocks = _DENSE_CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        feats = [
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(), nn.MaxPool2D(3, 2, padding=1),
        ]
        ch = init_c
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(blocks) - 1:
                feats.append(_Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.classifier(x)
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)


# GoogLeNet -----------------------------------------------------------------

class _BasicConv(nn.Sequential):
    def __init__(self, in_c, out_c, k, stride=1, padding=0):
        super().__init__(
            nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding, bias_attr=False),
            nn.BatchNorm2D(out_c), nn.ReLU(),
        )


class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _BasicConv(in_c, c1, 1)
        self.b2 = nn.Sequential(_BasicConv(in_c, c3r, 1), _BasicConv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_BasicConv(in_c, c5r, 1), _BasicConv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1), _BasicConv(in_c, proj, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class GoogLeNet(nn.Layer):
    """reference vision/models/googlenet.py:113 — returns (main, aux1, aux2)
    like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 64, 7, stride=2, padding=3), nn.MaxPool2D(3, 2, padding=1),
            _BasicConv(64, 64, 1), _BasicConv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2, padding=1),
        )
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
            # aux heads (train-time deep supervision)
            self.aux1 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), _BasicConv(512, 128, 1), nn.Flatten(),
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes),
            )
            self.aux2 = nn.Sequential(
                nn.AdaptiveAvgPool2D(4), _BasicConv(528, 128, 1), nn.Flatten(),
                nn.Linear(128 * 16, 1024), nn.ReLU(), nn.Dropout(0.7),
                nn.Linear(1024, num_classes),
            )

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        aux1 = self.aux1(x) if self.num_classes > 0 else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = self.aux2(x) if self.num_classes > 0 else None
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.fc(self.dropout(x))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return GoogLeNet(**kwargs)


# InceptionV3 ---------------------------------------------------------------

class _IncA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _BasicConv(in_c, 64, 1)
        self.b5 = nn.Sequential(_BasicConv(in_c, 48, 1), _BasicConv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(
            _BasicConv(in_c, 64, 1), _BasicConv(64, 96, 3, padding=1),
            _BasicConv(96, 96, 3, padding=1),
        )
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1), _BasicConv(in_c, pool_c, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], axis=1)


class _IncB(nn.Layer):  # grid reduction
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _BasicConv(in_c, 384, 3, stride=2)
        self.b3d = nn.Sequential(
            _BasicConv(in_c, 64, 1), _BasicConv(64, 96, 3, padding=1),
            _BasicConv(96, 96, 3, stride=2),
        )
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _IncC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _BasicConv(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _BasicConv(in_c, c7, 1),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, 192, (7, 1), padding=(3, 0)),
        )
        self.b7d = nn.Sequential(
            _BasicConv(in_c, c7, 1),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, c7, (1, 7), padding=(0, 3)),
            _BasicConv(c7, c7, (7, 1), padding=(3, 0)),
            _BasicConv(c7, 192, (1, 7), padding=(0, 3)),
        )
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1), _BasicConv(in_c, 192, 1))

    def forward(self, x):
        return paddle.concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)], axis=1)


class _IncD(nn.Layer):  # grid reduction 2
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_BasicConv(in_c, 192, 1), _BasicConv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _BasicConv(in_c, 192, 1),
            _BasicConv(192, 192, (1, 7), padding=(0, 3)),
            _BasicConv(192, 192, (7, 1), padding=(3, 0)),
            _BasicConv(192, 192, 3, stride=2),
        )
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return paddle.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _BasicConv(in_c, 320, 1)
        self.b3_stem = _BasicConv(in_c, 384, 1)
        self.b3_a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.bd_stem = nn.Sequential(_BasicConv(in_c, 448, 1), _BasicConv(448, 384, 3, padding=1))
        self.bd_a = _BasicConv(384, 384, (1, 3), padding=(0, 1))
        self.bd_b = _BasicConv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1), _BasicConv(in_c, 192, 1))

    def forward(self, x):
        s = self.b3_stem(x)
        d = self.bd_stem(x)
        return paddle.concat(
            [self.b1(x), self.b3_a(s), self.b3_b(s), self.bd_a(d), self.bd_b(d), self.bp(x)],
            axis=1,
        )


class InceptionV3(nn.Layer):
    """reference vision/models/inceptionv3.py:493"""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BasicConv(3, 32, 3, stride=2), _BasicConv(32, 32, 3),
            _BasicConv(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _BasicConv(64, 80, 1), _BasicConv(80, 192, 3), nn.MaxPool2D(3, 2),
        )
        self.blocks = nn.Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _IncB(288),
            _IncC(768, 128), _IncC(768, 160), _IncC(768, 160), _IncC(768, 192),
            _IncD(768),
            _IncE(1280), _IncE(2048),
        )
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.fc(self.dropout(x))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return InceptionV3(**kwargs)


# ShuffleNetV2 --------------------------------------------------------------

def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = paddle.reshape(x, [n, groups, c // groups, h, w])
    x = paddle.transpose(x, [0, 2, 1, 3, 4])
    return paddle.reshape(x, [n, c, h, w])


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act):
        super().__init__()
        self.stride = stride
        branch_c = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _ConvBNAct(in_c // 2, branch_c, 1, act=act),
                _ConvBNAct(branch_c, branch_c, 3, stride=1, groups=branch_c, act=None),
                _ConvBNAct(branch_c, branch_c, 1, act=act),
            )
        else:
            self.branch1 = nn.Sequential(
                _ConvBNAct(in_c, in_c, 3, stride=stride, groups=in_c, act=None),
                _ConvBNAct(in_c, branch_c, 1, act=act),
            )
            self.branch2 = nn.Sequential(
                _ConvBNAct(in_c, branch_c, 1, act=act),
                _ConvBNAct(branch_c, branch_c, 3, stride=stride, groups=branch_c, act=None),
                _ConvBNAct(branch_c, branch_c, 1, act=act),
            )

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1 = x[:, :half]
            x2 = x[:, half:]
            out = paddle.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {
    0.25: [24, 24, 48, 96, 512],
    0.33: [24, 32, 64, 128, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


class ShuffleNetV2(nn.Layer):
    """reference vision/models/shufflenetv2.py:173"""

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        act_layer = nn.Swish if act == "swish" else nn.ReLU
        chans = _SHUFFLE_CFG[scale]
        self.conv1 = _ConvBNAct(3, chans[0], 3, stride=2, act=act_layer)
        self.pool1 = nn.MaxPool2D(3, 2, padding=1)
        stages = []
        in_c = chans[0]
        for stage_i, repeat in enumerate([4, 8, 4]):
            out_c = chans[stage_i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act_layer)]
            for _ in range(repeat - 1):
                units.append(_ShuffleUnit(out_c, out_c, 1, act_layer))
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _ConvBNAct(in_c, chans[-1], 1, act=act_layer)
        if with_pool:
            self.pool_last = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chans[-1], num_classes)

    def forward(self, x):
        x = self.pool1(self.conv1(x))
        x = self.stages(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool_last(x)
        if self.num_classes > 0:
            x = paddle.flatten(x, 1)
            x = self.fc(x)
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
