"""Statistics ops (reference: python/paddle/tensor/stat.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ._ops_common import apply, ensure_tensor
from .math import _axis_arg, mean  # noqa: F401  (mean re-exported)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return apply(
        "std", lambda v: jnp.std(v, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim), x
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return apply(
        "var", lambda v: jnp.var(v, axis=ax, ddof=1 if unbiased else 0, keepdims=keepdim), x
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    if mode == "avg":
        return apply("median", lambda v: jnp.median(v, axis=ax, keepdims=keepdim), x)

    def _min_mode(v):
        # 'min' mode: lower of the two middle values, with index
        axis_ = ax if ax is not None else None
        if axis_ is None:
            flat = v.reshape(-1)
            n = flat.shape[0]
            idx_sorted = jnp.argsort(flat)
            mid = (n - 1) // 2
            i = idx_sorted[mid]
            return flat[i], i.astype(jnp.int32)
        vs = jnp.sort(v, axis=axis_)
        isort = jnp.argsort(v, axis=axis_)
        n = v.shape[axis_]
        mid = (n - 1) // 2
        val = jnp.take(vs, mid, axis=axis_)
        idx = jnp.take(isort, mid, axis=axis_).astype(jnp.int32)
        if keepdim:
            val = jnp.expand_dims(val, axis_)
            idx = jnp.expand_dims(idx, axis_)
        return val, idx

    return apply("median_min", _min_mode, x)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return apply("nanmedian", lambda v: jnp.nanmedian(v, axis=ax, keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    qv = q if not hasattr(q, "_value") else q._value

    def _q(v):
        out = jnp.quantile(
            v.astype(jnp.float32),
            jnp.asarray(qv),
            axis=ax,
            keepdims=keepdim,
            method=interpolation,
        )
        return out

    return apply("quantile", _q, x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return apply(
        "nanquantile",
        lambda v: jnp.nanquantile(
            v.astype(jnp.float32), jnp.asarray(q), axis=ax, keepdims=keepdim, method=interpolation
        ),
        x,
    )
