"""Logic/comparison ops (reference: python/paddle/tensor/logic.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ._ops_common import Tensor, apply, binary, ensure_tensor, unary

equal = binary("equal", jnp.equal)
not_equal = binary("not_equal", jnp.not_equal)
greater_than = binary("greater_than", jnp.greater)
greater_equal = binary("greater_equal", jnp.greater_equal)
less_than = binary("less_than", jnp.less)
less_equal = binary("less_equal", jnp.less_equal)
logical_and = binary("logical_and", jnp.logical_and)
logical_or = binary("logical_or", jnp.logical_or)
logical_xor = binary("logical_xor", jnp.logical_xor)
logical_not = unary("logical_not", jnp.logical_not)
bitwise_and = binary("bitwise_and", jnp.bitwise_and)
bitwise_or = binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = binary("bitwise_xor", jnp.bitwise_xor)
bitwise_not = unary("bitwise_not", jnp.bitwise_not)
bitwise_left_shift = binary("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = binary("bitwise_right_shift", jnp.right_shift)


def equal_all(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("equal_all", lambda a, b: jnp.asarray(a.shape == b.shape and bool(jnp.all(a == b)) if not _traced(a, b) else jnp.all(a == b)), x, y)


def _traced(*vs):
    import jax

    return any(isinstance(v, jax.core.Tracer) for v in vs)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply(
        "allclose",
        lambda a, b: jnp.allclose(a, b, rtol=float(rtol), atol=float(atol), equal_nan=equal_nan),
        x,
        y,
    )


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply(
        "isclose",
        lambda a, b: jnp.isclose(a, b, rtol=float(rtol), atol=float(atol), equal_nan=equal_nan),
        x,
        y,
    )


def is_empty(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def in1d(x, test_x, assume_unique=False, invert=False, name=None):
    x, test_x = ensure_tensor(x), ensure_tensor(test_x)
    return apply("in1d", lambda a, b: jnp.isin(a.reshape(-1), b, invert=invert), x, test_x)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    x, test_x = ensure_tensor(x), ensure_tensor(test_x)
    return apply("isin", lambda a, b: jnp.isin(a, b, invert=invert), x, test_x)
