"""Random ops (reference: python/paddle/tensor/random.py).

Stateful paddle semantics over functional jax PRNG: each call consumes a key
from the global generator (paddle_tpu._core.random).  Inside a jitted train
step wrapped with `key_scope`, keys derive from the traced per-step key.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core import random as rng
from paddle_tpu._core.dtype import to_jax_dtype
from paddle_tpu._core import flags
from ._ops_common import Tensor, ensure_tensor


def _default_float():
    return to_jax_dtype(flags.flag("FLAGS_default_dtype"))


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._value)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    dt = to_jax_dtype(dtype) or _default_float()
    key = jax.random.key(seed) if seed else rng.next_key()
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return Tensor(jax.random.uniform(key, _shape_list(shape), dt, lo, hi))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    x = ensure_tensor(x)
    x._bind(uniform(x.shape, x._value.dtype, min, max, seed)._value)
    return x


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    dt = to_jax_dtype(dtype) or _default_float()
    return Tensor(jax.random.normal(rng.next_key(), _shape_list(shape), dt))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        sh = jnp.broadcast_shapes(
            jnp.shape(m) if hasattr(m, "shape") else (), jnp.shape(s) if hasattr(s, "shape") else ()
        )
        return Tensor(jax.random.normal(rng.next_key(), sh) * s + m)
    sh = _shape_list(shape) if shape is not None else []
    return Tensor(jax.random.normal(rng.next_key(), sh) * std + mean)


def normal_(x, mean=0.0, std=1.0, name=None):
    x = ensure_tensor(x)
    x._bind(jax.random.normal(rng.next_key(), x._value.shape, x._value.dtype) * std + mean)
    return x


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    dt = to_jax_dtype(dtype) or _default_float()
    key = jax.random.key(seed) if seed else rng.next_key()
    return Tensor(jax.random.normal(key, _shape_list(shape), dt) * std + mean)


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def standard_gamma(alpha, name=None):
    alpha = ensure_tensor(alpha)
    return Tensor(jax.random.gamma(rng.next_key(), alpha._value))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = to_jax_dtype(dtype) or jnp.int32
    return Tensor(jax.random.randint(rng.next_key(), _shape_list(shape), low, high, dt))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return randint(low, high, x.shape, dtype or x._value.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(rng.next_key(), n).astype(to_jax_dtype(dtype)))


def shuffle(x, axis=0, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.random.permutation(rng.next_key(), x._value, axis=axis, independent=False))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    v = x._value
    probs = v / jnp.sum(v, axis=-1, keepdims=True)
    if replacement:
        out = jax.random.categorical(
            rng.next_key(), jnp.log(jnp.maximum(probs, 1e-30)), shape=(num_samples,) + v.shape[:-1]
        )
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k for sampling without replacement.
        g = jax.random.gumbel(rng.next_key(), v.shape)
        scores = jnp.log(jnp.maximum(probs, 1e-30)) + g
        out = jnp.argsort(-scores, axis=-1)[..., :num_samples]
    return Tensor(out.astype(jnp.int32))


def bernoulli(x, name=None):
    x = ensure_tensor(x)
    return Tensor(
        jax.random.bernoulli(rng.next_key(), x._value).astype(x._value.dtype)
    )


def bernoulli_(x, p=0.5, name=None):
    x = ensure_tensor(x)
    x._bind(jax.random.bernoulli(rng.next_key(), p, x._value.shape).astype(x._value.dtype))
    return x


def poisson(x, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.random.poisson(rng.next_key(), x._value).astype(x._value.dtype))


def binomial(count, prob, name=None):
    count, prob = ensure_tensor(count), ensure_tensor(prob)
    return Tensor(
        jax.random.binomial(rng.next_key(), count._value.astype(jnp.float32), prob._value).astype(jnp.int32)
    )


def exponential_(x, lam=1.0, name=None):
    x = ensure_tensor(x)
    x._bind((jax.random.exponential(rng.next_key(), x._value.shape, x._value.dtype) / lam))
    return x


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    sh = _shape_list(shape) if shape is not None else []
    return Tensor(jnp.exp(jax.random.normal(rng.next_key(), sh) * std + mean))


def rayleigh(scale=1.0, shape=None, name=None):
    if isinstance(scale, Tensor):
        # tensor scale: one sample per element (broadcasting a single
        # scalar draw over the tensor would correlate every entry)
        sv = scale._value
        sh = _shape_list(shape) if shape is not None else list(sv.shape)
        u = jax.random.uniform(rng.next_key(), sh, minval=1e-9, maxval=1.0)
        return Tensor(sv * jnp.sqrt(-2.0 * jnp.log(u)))
    sh = _shape_list(shape) if shape is not None else []
    u = jax.random.uniform(rng.next_key(), sh, minval=1e-9, maxval=1.0)
    return Tensor(scale * jnp.sqrt(-2.0 * jnp.log(u)))


def cauchy_(x, loc=0, scale=1, name=None):
    """Fill x in place with Cauchy(loc, scale) samples (reference:
    Tensor.cauchy_); inverse-CDF sampling on the VPU."""
    x = ensure_tensor(x)
    u = jax.random.uniform(rng.next_key(), x._value.shape, jnp.float32)
    val = jnp.float32(loc) + jnp.float32(scale) * jnp.tan(jnp.pi * (u - 0.5))
    x._bind(val.astype(x._value.dtype))
    return x


def geometric_(x, probs, name=None):
    """Fill x in place with Geometric(probs) samples on {1, 2, ...}
    (reference: Tensor.geometric_)."""
    x = ensure_tensor(x)
    if isinstance(probs, Tensor):
        probs = probs._value
    u = jax.random.uniform(rng.next_key(), x._value.shape, jnp.float32,
                           minval=jnp.float32(1e-7), maxval=1.0)
    val = jnp.floor(jnp.log(u) / jnp.log1p(-jnp.float32(probs))) + 1.0
    x._bind(val.astype(x._value.dtype))
    return x
