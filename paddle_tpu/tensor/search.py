"""Search/sort ops (reference: python/paddle/tensor/search.py)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core.dtype import to_jax_dtype
from ._ops_common import Tensor, apply, ensure_tensor


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)
    dt = to_jax_dtype(dtype)

    def _am(v):
        if axis is None:
            return jnp.argmax(v.reshape(-1)).astype(dt)
        out = jnp.argmax(v, axis=int(axis)).astype(dt)
        return jnp.expand_dims(out, int(axis)) if keepdim else out

    return apply("argmax", _am, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)
    dt = to_jax_dtype(dtype)

    def _am(v):
        if axis is None:
            return jnp.argmin(v.reshape(-1)).astype(dt)
        out = jnp.argmin(v, axis=int(axis)).astype(dt)
        return jnp.expand_dims(out, int(axis)) if keepdim else out

    return apply("argmin", _am, x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    x = ensure_tensor(x)

    def _as(v):
        idx = jnp.argsort(v, axis=axis, stable=stable, descending=descending)
        return idx.astype(jnp.int32)

    return apply("argsort", _as, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    x = ensure_tensor(x)

    def _sort(v):
        out = jnp.sort(v, axis=axis, stable=stable, descending=descending)
        return out

    return apply("sort", _sort, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):  # noqa: A002
    x = ensure_tensor(x)
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)

    def _topk(v):
        ax = -1 if axis is None else int(axis)
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, kk)
        else:
            vals, idx = jax.lax.top_k(-vm, kk)
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int32), -1, ax)

    return apply("topk", _topk, x)


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    x, y = ensure_tensor(x, ref=None), ensure_tensor(y)
    return apply("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def where_(condition, x, y, name=None):
    out = where(condition, x, y)
    x = ensure_tensor(x)
    x._bind(out._value)
    return x


def nonzero(x, as_tuple=False):
    x = ensure_tensor(x)
    from paddle_tpu.tensor._ops_common import reject_tracers

    reject_tracers(
        "nonzero",
        "The count of nonzeros is data-dependent; use boolean masks "
        "(paddle.where with full shapes) inside compiled code.",
        x,
    )
    nz = jnp.nonzero(x._value)  # concrete: executes on device
    if as_tuple:
        return tuple(Tensor(n.astype(jnp.int32)[:, None]) for n in nz)
    return Tensor(jnp.stack(nz, axis=1).astype(jnp.int32))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)

    def _kth(v):
        vals = jnp.sort(v, axis=axis)
        idxs = jnp.argsort(v, axis=axis, stable=True)
        sel_v = jnp.take(vals, k - 1, axis=axis)
        sel_i = jnp.take(idxs, k - 1, axis=axis).astype(jnp.int32)
        if keepdim:
            sel_v = jnp.expand_dims(sel_v, axis)
            sel_i = jnp.expand_dims(sel_i, axis)
        return sel_v, sel_i

    return apply("kthvalue", _kth, x)


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value per slice; ties pick the LARGEST value, index is
    its LAST occurrence (reference mode kernel semantics).  Traceable: an
    O(n^2) pairwise-count formulation replaces the round-1 numpy loop."""
    x = ensure_tensor(x)

    def _mode(v):
        vm = jnp.moveaxis(v, axis, -1)
        eq = vm[..., :, None] == vm[..., None, :]
        counts = eq.sum(-1)  # occurrences of each element
        maxc = counts.max(-1, keepdims=True)
        is_best = counts == maxc
        # largest value among max-count candidates
        if jnp.issubdtype(vm.dtype, jnp.inexact):
            lowest = jnp.asarray(-jnp.inf, vm.dtype)
        else:
            lowest = jnp.iinfo(vm.dtype).min
        best = jnp.max(jnp.where(is_best, vm, lowest), axis=-1, keepdims=True)
        # last occurrence index of the winning value
        hit = vm == best
        n = vm.shape[-1]
        pos = jnp.arange(n, dtype=jnp.int32)
        idx = jnp.max(jnp.where(hit, pos, -1), axis=-1)
        return best[..., 0], idx

    v_out, i_out = apply("mode", _mode, x)
    if keepdim:
        from .manipulation import unsqueeze

        v_out = unsqueeze(v_out, axis)
        i_out = unsqueeze(i_out, axis)
    return v_out, i_out


def masked_select(x, mask, name=None):
    from .manipulation import masked_select as _ms

    return _ms(x, mask, name)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    sorted_sequence, values = ensure_tensor(sorted_sequence), ensure_tensor(values)
    side = "right" if right else "left"
    dt = jnp.int32  # out_int32 kept for API parity; int64 narrows to int32 anyway

    def _ss(seq, v):
        if seq.ndim == 1:
            return jnp.searchsorted(seq, v, side=side).astype(dt)
        # batched: apply along leading dims
        fn = lambda s, vv: jnp.searchsorted(s, vv, side=side)  # noqa: E731
        for _ in range(seq.ndim - 1):
            fn = jax.vmap(fn)
        return fn(seq, v).astype(dt)

    return apply("searchsorted", _ss, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    x, sorted_sequence = ensure_tensor(x), ensure_tensor(sorted_sequence)
    side = "right" if right else "left"
    dt = jnp.int32  # out_int32 kept for API parity; int64 narrows to int32 anyway
    return apply(
        "bucketize", lambda v, seq: jnp.searchsorted(seq, v, side=side).astype(dt), x, sorted_sequence
    )


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus (top-p) sampling over the last axis (reference:
    paddle.tensor.top_p_sampling, paddle/phi/kernels/gpu/top_p_sampling
    kernel — the serving sampler).  x: [B, V] probabilities, ps: [B] or
    [B, 1] cumulative-probability cutoffs.  Returns (scores, ids)."""
    from paddle_tpu._core import random as rng

    x, ps = ensure_tensor(x), ensure_tensor(ps)
    key = jax.random.key(seed) if seed not in (None, -1) else rng.next_key()

    def _fn(v, p):
        probs = v.astype(jnp.float32)
        p = p.reshape(-1, 1).astype(jnp.float32)
        sort_p = jnp.sort(probs, axis=-1)[:, ::-1]
        sort_i = jnp.argsort(-probs, axis=-1)
        cum = jnp.cumsum(sort_p, axis=-1)
        # keep the smallest prefix with cumsum >= p (always keep top-1)
        keep = (cum - sort_p) < p
        keep = keep.at[:, 0].set(True)
        masked = jnp.where(keep, sort_p, 0.0)
        masked = masked / jnp.sum(masked, axis=-1, keepdims=True)
        choice = jax.random.categorical(key, jnp.log(jnp.clip(masked, 1e-38)), axis=-1)
        ids = jnp.take_along_axis(sort_i, choice[:, None], axis=-1)
        scores = jnp.take_along_axis(probs, ids, axis=-1).astype(v.dtype)
        return scores, ids.astype(jnp.int32)

    return apply("top_p_sampling", _fn, x, ps, n_outputs=2)
