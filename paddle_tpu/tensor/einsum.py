"""Einsum (reference: python/paddle/tensor/einsum.py — a 1000-line planner;
here jnp.einsum lowers straight to dot_general, which XLA maps to the MXU)."""

from __future__ import annotations

import jax.numpy as jnp

from ._ops_common import apply, ensure_tensor


def einsum(equation, *operands):
    tensors = [ensure_tensor(t) for t in operands]
    return apply("einsum", lambda *vs: jnp.einsum(equation, *vs), *tensors)
