"""Creation ops (reference: python/paddle/tensor/creation.py, 44 functions)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core.dtype import to_jax_dtype
from paddle_tpu._core.tensor import Tensor, to_tensor
from paddle_tpu._core import flags
from ._ops_common import apply, ensure_tensor

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "empty",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "meshgrid",
    "diag",
    "diagflat",
    "diag_embed",
    "tril",
    "triu",
    "tril_indices",
    "triu_indices",
    "assign",
    "clone",
    "complex",
    "polar",
    "one_hot",
]


def _default_float():
    return to_jax_dtype(flags.flag("FLAGS_default_dtype"))


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._value)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype is not None else _default_float()
    return Tensor(jnp.zeros(_shape_list(shape), dt))


def ones(shape, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype is not None else _default_float()
    return Tensor(jnp.ones(_shape_list(shape), dt))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is not None:
        dt = to_jax_dtype(dtype)
    else:
        dt = _default_float() if isinstance(fill_value, float) else (
            jnp.bool_ if isinstance(fill_value, bool) else jnp.int32
        )
    return Tensor(jnp.full(_shape_list(shape), fill_value, dt))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.zeros(x._value.shape, to_jax_dtype(dtype) or x._value.dtype))


def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.ones(x._value.shape, to_jax_dtype(dtype) or x._value.dtype))


def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.full(x._value.shape, fill_value, to_jax_dtype(dtype) or x._value.dtype))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _scalar(v):
        return v.item() if isinstance(v, Tensor) else v

    start, end, step = _scalar(start), _scalar(end), _scalar(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            "float32"
            if any(isinstance(v, float) for v in (start, end, step))
            else "int64"
        )
    return Tensor(jnp.arange(start, end, step, to_jax_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype is not None else _default_float()
    s = start.item() if isinstance(start, Tensor) else start
    e = stop.item() if isinstance(stop, Tensor) else stop
    n = num.item() if isinstance(num, Tensor) else num
    return Tensor(jnp.linspace(s, e, int(n), dtype=dt))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype is not None else _default_float()
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=float(base), dtype=dt))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype is not None else _default_float()
    return Tensor(jnp.eye(int(num_rows), None if num_columns is None else int(num_columns), dtype=dt))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    tensors = [ensure_tensor(a) for a in args]
    return apply("meshgrid", lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")), *tensors)


def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)

    def _diag(v):
        if v.ndim == 1:
            out = jnp.diag(v, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(v, dtype=bool), k=offset)
                out = jnp.where(mask, out, jnp.asarray(padding_value, v.dtype))
            return out
        return jnp.diagonal(v, offset=offset)

    return apply("diag", _diag, x)


def diagflat(x, offset=0, name=None):
    x = ensure_tensor(x)
    return apply("diagflat", lambda v: jnp.diagflat(v, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    x = ensure_tensor(x)

    def _embed(v):
        n = v.shape[-1] + abs(offset)
        base = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        rows = idx + max(0, -offset)
        cols = idx + max(0, offset)
        out = base.at[..., rows, cols].set(v)
        if (dim1, dim2) != (-2, -1):
            nd = out.ndim
            d1, d2 = dim1 % nd, dim2 % nd
            perm = [d for d in range(nd) if d not in (d1, d2)]
            order = list(range(nd - 2)) + [nd - 2, nd - 1]
            full = perm + [d1, d2]
            inv = [0] * nd
            for i, p in enumerate(full):
                inv[p] = order[i]
            out = jnp.transpose(out, inv)
        return out

    return apply("diag_embed", _embed, x)


def tril(x, diagonal=0, name=None):
    x = ensure_tensor(x)
    return apply("tril", lambda v: jnp.tril(v, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    x = ensure_tensor(x)
    return apply("triu", lambda v: jnp.triu(v, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), to_jax_dtype(dtype)))


def assign(x, output=None):
    x = ensure_tensor(x) if not isinstance(x, (list, tuple, np.ndarray, int, float)) else to_tensor(x)
    out = apply("assign", lambda v: v + jnp.zeros((), v.dtype), x)
    if output is not None:
        output._bind(out._value)
        return output
    return out


def clone(x, name=None):
    return ensure_tensor(x).clone()


def complex(real, imag, name=None):
    real, imag = ensure_tensor(real), ensure_tensor(imag)
    return apply("complex", lambda r, i: jax.lax.complex(r, i), real, imag)


def polar(abs, angle, name=None):
    abs, angle = ensure_tensor(abs), ensure_tensor(angle)
    return apply(
        "polar", lambda a, t: jax.lax.complex(a * jnp.cos(t), a * jnp.sin(t)), abs, angle
    )


def one_hot(x, num_classes, name=None):
    x = ensure_tensor(x)
    return apply(
        "one_hot",
        lambda v: jax.nn.one_hot(v, num_classes, dtype=jnp.float32),
        x,
    )



def create_tensor(dtype, name=None, persistable=False):
    """Empty 0-d tensor holder of the given dtype (reference:
    python/paddle/tensor/creation.py:233)."""
    t = Tensor(jnp.zeros((), to_jax_dtype(dtype)))
    t.name = name or ""
    t.persistable = persistable
    return t
