"""Attribute ops (reference: python/paddle/tensor/attribute.py)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.dtype import is_complex_dtype, is_floating_dtype, is_integer_dtype
from ._ops_common import Tensor, apply, ensure_tensor
from .manipulation import rank, shape  # noqa: F401
from .math import imag, real  # noqa: F401


def is_floating_point(x):
    return is_floating_dtype(ensure_tensor(x).dtype)


def is_integer(x):
    return is_integer_dtype(ensure_tensor(x).dtype)


def is_complex(x):
    return is_complex_dtype(ensure_tensor(x).dtype)
