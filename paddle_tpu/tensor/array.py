"""TensorArray ops (reference paddle/phi/core/tensor_array.cc + python
paddle.tensor.array_* in python/paddle/tensor/array.py).

TPU-native: in dygraph a TensorArray is a python list of Tensors (exactly
the reference's dygraph behavior); under jit/to_static, writes at traced
indices are rejected with guidance to use lax.scan-style ops — XLA has no
dynamically-sized containers (the reference's static-graph TensorArray is
the LoDTensorArray variable consumed by while_op, which this framework's
while_loop replaces with carried state)."""

from __future__ import annotations

import jax

from ._ops_common import Tensor, ensure_tensor

__all__ = ["create_array", "array_write", "array_read", "array_length"]


def create_array(dtype="float32", initialized_list=None):
    arr = list(initialized_list) if initialized_list else []
    return [ensure_tensor(x) for x in arr]


def _concrete_index(i, op):
    v = i._value if isinstance(i, Tensor) else i
    if isinstance(v, jax.core.Tracer):
        raise RuntimeError(
            f"{op} with a traced index is not supported under jit (XLA has no "
            "dynamic containers); carry state through static.nn.while_loop / "
            "lax.scan instead"
        )
    return int(v)


def array_write(x, i, array=None):
    x = ensure_tensor(x)
    if array is None:
        array = []
    idx = _concrete_index(i, "array_write")
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    return array[_concrete_index(i, "array_read")]


def array_length(array):
    import jax.numpy as jnp

    return Tensor(jnp.asarray(len(array), jnp.int32))
