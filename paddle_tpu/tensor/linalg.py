"""Linear algebra ops (reference: python/paddle/tensor/linalg.py, 61 fns).

Decompositions route to jax.numpy.linalg / jax.scipy.linalg — XLA provides
TPU/CPU implementations; matmul-class ops lower to dot_general (MXU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ._ops_common import Tensor, apply, ensure_tensor
from .math import bmm, dot, matmul, mm, mv  # re-export  # noqa: F401


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)

    def _norm(v):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.real(v * jnp.conj(v)))).astype(v.dtype)
            return jnp.linalg.norm(v, ord=None, axis=_ax(axis), keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(v, ord="nuc", axis=_ax(axis), keepdims=keepdim)
        if p == float("inf") or p == "inf":
            ordv = jnp.inf
        elif p == float("-inf") or p == "-inf":
            ordv = -jnp.inf
        else:
            ordv = p
        if axis is None:
            return jnp.linalg.norm(v.reshape(-1), ord=ordv, keepdims=keepdim)
        ax = _ax(axis)
        if isinstance(ax, tuple) and len(ax) == 1:
            ax = ax[0]
        if isinstance(ax, int):
            # vector norm along one axis
            if ordv == jnp.inf:
                return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
            if ordv == -jnp.inf:
                return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
            if ordv == 0:
                return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
            return jnp.sum(jnp.abs(v) ** ordv, axis=ax, keepdims=keepdim) ** (1.0 / ordv)
        return jnp.linalg.norm(v, ord=ordv, axis=ax, keepdims=keepdim)

    return apply("norm", _norm, x)


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply(
        "vector_norm",
        lambda v: jnp.linalg.vector_norm(v, ord=p, axis=_ax(axis), keepdims=keepdim),
        x,
    )


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    x = ensure_tensor(x)
    ordv = {"fro": "fro", "nuc": "nuc"}.get(p, p)
    return apply(
        "matrix_norm",
        lambda v: jnp.linalg.norm(v, ord=ordv, axis=tuple(axis), keepdims=keepdim),
        x,
    )


def dist(x, y, p=2, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def _dist(a, b):
        d = a - b
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)

    return apply("dist", _dist, x, y)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def _cdist(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 0.0)
        return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)

    return apply("cdist", _cdist, x, y)


def cholesky(x, upper=False, name=None):
    x = ensure_tensor(x)
    return apply(
        "cholesky",
        lambda v: jnp.linalg.cholesky(v) if not upper else jnp.swapaxes(jnp.linalg.cholesky(v), -1, -2).conj(),
        x,
    )


def cholesky_solve(x, y, upper=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def _cs(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return apply("cholesky_solve", _cs, x, y)


def qr(x, mode="reduced", name=None):
    x = ensure_tensor(x)
    if mode == "r":
        return apply("qr", lambda v: jnp.linalg.qr(v, mode="r"), x)
    return apply("qr", lambda v: tuple(jnp.linalg.qr(v, mode=mode)), x)


def svd(x, full_matrices=False, name=None):
    x = ensure_tensor(x)
    return apply("svd", lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)), x)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    x = ensure_tensor(x)

    def _svdl(v):
        u, s, vt = jnp.linalg.svd(v, full_matrices=False)
        k = min(q, s.shape[-1])
        return u[..., :k], s[..., :k], jnp.swapaxes(vt, -1, -2)[..., :k]

    return apply("svd_lowrank", _svdl, x)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = ensure_tensor(x)
    qq = q if q is not None else min(6, *x.shape[-2:])

    def _pca(v):
        if center:
            v = v - jnp.mean(v, axis=-2, keepdims=True)
        u, s, vt = jnp.linalg.svd(v, full_matrices=False)
        return u[..., :qq], s[..., :qq], jnp.swapaxes(vt, -1, -2)[..., :qq]

    return apply("pca_lowrank", _pca, x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = ensure_tensor(x)
    return apply("matrix_rank", lambda v: jnp.linalg.matrix_rank(v, rtol=tol), x)


def matrix_power(x, n, name=None):
    x = ensure_tensor(x)
    return apply("matrix_power", lambda v: jnp.linalg.matrix_power(v, n), x)


def inv(x, name=None):
    x = ensure_tensor(x)
    return apply("inv", jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    x = ensure_tensor(x)
    return apply("pinv", lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), x)


def solve(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply("solve", jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply(
        "triangular_solve",
        lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular
        ),
        x,
        y,
    )


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def _lstsq(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv

    return apply("lstsq", _lstsq, x, y)


def lu(x, pivot=True, get_infos=False, name=None):
    x = ensure_tensor(x)

    def _lu(v):
        lu_mat, piv = jax.scipy.linalg.lu_factor(v)
        if get_infos:
            return lu_mat, piv.astype(jnp.int32) + 1, jnp.zeros((), jnp.int32)
        return lu_mat, piv.astype(jnp.int32) + 1

    return apply("lu", _lu, x)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    lu_data, lu_pivots = ensure_tensor(lu_data), ensure_tensor(lu_pivots)

    def _unpack(lu_mat, piv):
        m, n = lu_mat.shape[-2], lu_mat.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_mat[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_mat.dtype)
        U = jnp.triu(lu_mat[..., :k, :])
        # build permutation from pivots (1-based sequential swaps)
        p = jnp.arange(m)
        piv0 = piv - 1

        def body(i, p):
            j = piv0[i]
            pi, pj = p[i], p[j]
            p = p.at[i].set(pj).at[j].set(pi)
            return p

        p = jax.lax.fori_loop(0, piv0.shape[-1], body, p)
        P = jnp.eye(m, dtype=lu_mat.dtype)[p].T
        return P, L, U

    return apply("lu_unpack", _unpack, lu_data, lu_pivots)


def eig(x, name=None):
    """Non-symmetric eigendecomposition.  Host LAPACK only: XLA:TPU has no
    nonsymmetric eig (the reference's is cuSOLVER); documented eager-only —
    use eigh for the hermitian case under jit."""
    x = ensure_tensor(x)
    import numpy as np

    from paddle_tpu.tensor._ops_common import reject_tracers

    reject_tracers("eig", "Use paddle.linalg.eigh for hermitian matrices under jit.", x)
    w, v = np.linalg.eig(np.asarray(x._value))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    x = ensure_tensor(x)
    import numpy as np

    from paddle_tpu.tensor._ops_common import reject_tracers

    reject_tracers("eigvals", "Use paddle.linalg.eigvalsh for hermitian matrices under jit.", x)
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(x._value))))


def eigh(x, UPLO="L", name=None):
    x = ensure_tensor(x)
    return apply("eigh", lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), x)


def eigvalsh(x, UPLO="L", name=None):
    x = ensure_tensor(x)
    return apply("eigvalsh", lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), x)


def det(x, name=None):
    x = ensure_tensor(x)
    return apply("det", jnp.linalg.det, x)


def slogdet(x, name=None):
    x = ensure_tensor(x)
    return apply("slogdet", lambda v: tuple(jnp.linalg.slogdet(v)), x)


def cross(x, y, axis=9, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    ax = axis if axis != 9 else None

    def _cross(a, b):
        if ax is None:
            # first axis of length 3 (paddle semantics)
            for d in range(a.ndim):
                if a.shape[d] == 3:
                    return jnp.cross(a, b, axis=d)
            raise ValueError("no axis of size 3 found for cross()")
        return jnp.cross(a, b, axis=ax)

    return apply("cross", _cross, x, y)


def householder_product(x, tau, name=None):
    x, tau = ensure_tensor(x), ensure_tensor(tau)

    def _hp(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(t.shape[-1]):
            v = jnp.concatenate([jnp.zeros((i,), a.dtype), jnp.ones((1,), a.dtype), a[i + 1 :, i]])
            q = q - t[i] * (q @ jnp.outer(v, v))
        return q[:, :n]

    return apply("householder_product", _hp, x, tau)


def corrcoef(x, rowvar=True, name=None):
    x = ensure_tensor(x)
    return apply("corrcoef", lambda v: jnp.corrcoef(v, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    x = ensure_tensor(x)
    return apply(
        "cov",
        lambda v: jnp.cov(
            v,
            rowvar=rowvar,
            ddof=1 if ddof else 0,
            fweights=None if fweights is None else ensure_tensor(fweights)._value,
            aweights=None if aweights is None else ensure_tensor(aweights)._value,
        ),
        x,
    )


def matrix_exp(x, name=None):
    x = ensure_tensor(x)
    return apply("matrix_exp", jax.scipy.linalg.expm, x)


def orthogonalize(x, name=None):
    x = ensure_tensor(x)
    return apply("orthogonalize", lambda v: jnp.linalg.qr(v)[0], x)


def multi_dot(x, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return apply("multi_dot", lambda *vs: jnp.linalg.multi_dot(list(vs)), *tensors)


def inverse(x, name=None):
    """Alias of linalg.inv (reference: paddle.inverse)."""
    return inv(x)


def cond(x, p=None, name=None):
    """Matrix condition number (reference: paddle.linalg.cond): p in
    {None/'fro', 'nuc', 1, -1, 2, -2, inf, -inf}."""
    x = ensure_tensor(x)

    def _fn(v):
        vf = v.astype(jnp.float32)
        if p is None or p == 2 or p == -2:
            s = jnp.linalg.svd(vf, compute_uv=False)
            if p == -2:
                return (s[..., -1] / s[..., 0]).astype(v.dtype)
            return (s[..., 0] / s[..., -1]).astype(v.dtype)
        if p == "fro":
            n = jnp.sqrt(jnp.sum(vf * vf, axis=(-2, -1)))
            ninv = jnp.sqrt(jnp.sum(jnp.linalg.inv(vf) ** 2, axis=(-2, -1)))
            return (n * ninv).astype(v.dtype)
        if p == "nuc":
            s = jnp.linalg.svd(vf, compute_uv=False)
            si = jnp.linalg.svd(jnp.linalg.inv(vf), compute_uv=False)
            return (jnp.sum(s, -1) * jnp.sum(si, -1)).astype(v.dtype)
        # 1-norm: max over columns of column sums (sum rows, axis=-2);
        # inf-norm: max over rows of row sums (sum cols, axis=-1)
        axis = -2 if p in (1, -1) else -1
        red = jnp.max if p in (1, float("inf")) else jnp.min
        n = red(jnp.sum(jnp.abs(vf), axis=axis), axis=-1)
        ninv = red(jnp.sum(jnp.abs(jnp.linalg.inv(vf)), axis=axis), axis=-1)
        return (n * ninv).astype(v.dtype)

    return apply("cond", _fn, x)
