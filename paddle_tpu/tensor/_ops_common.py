"""Shared helpers for defining tensor ops.

The reference drives its op surface from YAML codegen
(paddle/phi/api/yaml/ops.yaml -> generated C++ + pybind).  Here every op is a
pure jax function routed through the autograd tape via
`paddle_tpu._core.autograd.apply` — jax.vjp is the generated-backward
equivalent, XLA the kernel library.
"""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.autograd import apply
from paddle_tpu._core.dtype import to_jax_dtype
from paddle_tpu._core.tensor import Tensor

__all__ = ["apply", "wrap", "ensure_tensor", "unary", "binary", "to_jax_dtype", "Tensor", "jnp"]


def ensure_tensor(x, ref=None):
    """Coerce python scalars / numpy arrays to Tensor (for binary op operands)."""
    if isinstance(x, Tensor):
        return x
    if ref is not None and isinstance(x, (int, float, bool)) and not isinstance(x, bool):
        # Match paddle scalar-promotion: scalar takes the tensor's dtype when
        # that preserves value semantics (float scalar + int tensor -> float).
        ref_dt = ref._value.dtype
        if isinstance(x, float) and not jnp.issubdtype(ref_dt, jnp.inexact):
            return Tensor(jnp.asarray(x, jnp.float32))
        return Tensor(jnp.asarray(x, ref_dt))
    from paddle_tpu._core.tensor import to_tensor

    return to_tensor(x)


def wrap(name, jfn):
    """Build a tensor-level op from a jax fn: op(*tensors, **static_kwargs)."""

    def op(*args, **kwargs):
        return apply(name, jfn, *args, **kwargs)

    op.__name__ = name
    return op


def unary(name, jfn, doc=""):
    op_name = name  # the paddle-API `name=` kwarg must NOT shadow the op id

    def op(x, name_arg=None, name=None):
        x = ensure_tensor(x)
        return apply(op_name, jfn, x)

    op.__name__ = name
    op.__doc__ = doc or f"Elementwise {name} (TPU-native equivalent of paddle.{name})."
    return op


def binary(name, jfn, doc=""):
    op_name = name  # NOT the call-time `name=` kwarg (AMP lists + static
    # capture + profiler all key off the op id; shadowing recorded None)

    def op(x, y, name=None):
        if not isinstance(x, Tensor) and isinstance(y, Tensor):
            x = ensure_tensor(x, ref=y)
        x = ensure_tensor(x)
        y = ensure_tensor(y, ref=x)
        return apply(op_name, jfn, x, y)

    op.__name__ = name
    op.__doc__ = doc or f"Elementwise {name} with numpy broadcasting (paddle.{name})."
    return op


class DynamicShapeError(RuntimeError):
    """Raised when a data-dependent-output-shape op is used under tracing.

    XLA requires static shapes (SURVEY.md §7 design stance); the reference's
    CUDA kernels can size outputs at runtime, this framework cannot.  Eager
    calls still work (concrete values); under jit/to_static use the suggested
    static-shape alternative.
    """


def reject_tracers(op_name: str, hint: str, *tensors):
    import jax

    for t in tensors:
        v = t._value if isinstance(t, Tensor) else t
        if isinstance(v, jax.core.Tracer):
            raise DynamicShapeError(
                f"paddle.{op_name} has a data-dependent output shape and "
                f"cannot run under jit/to_static (XLA needs static shapes). "
                f"{hint}"
            )


def inplace_from(x, base_fn, *args, **kwargs):
    """In-place rebind helper: runs the functional op on an ALIAS carrying
    x's old autograd identity (rebinding x's own node onto itself would
    self-loop the tape), then binds the result back into x.  With autograd
    ON, leaf tensors requiring grad reject in-place ops (their pre-op value
    is needed for their own grad accumulation — reference semantics); under
    no_grad() leaf mutation is the normal manual-optimizer pattern."""
    from paddle_tpu._core.autograd import is_grad_enabled

    if is_grad_enabled() and not x.stop_gradient and x._grad_node is None:
        raise RuntimeError(
            f"{base_fn.__name__}_: a leaf Tensor that requires grad cannot "
            f"be used in an in-place operation; use the functional form or "
            f"wrap the update in paddle.no_grad()"
        )
    alias = Tensor(x._value, stop_gradient=x.stop_gradient)
    alias._grad_node = x._grad_node
    alias._out_index = x._out_index
    out = base_fn(alias, *args, **kwargs)
    x._bind(out._value)
    x._grad_node, x._out_index = out._grad_node, out._out_index
    x.stop_gradient = out.stop_gradient and x.stop_gradient
    return x
