"""Math ops (reference: python/paddle/tensor/math.py, ~142 functions).

Every op is a pure jax.numpy function routed through the autograd tape; XLA
fuses elementwise chains into matmul epilogues on TPU, which is the whole
fusion story the reference builds CINN for.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core.dtype import to_jax_dtype
from ._ops_common import Tensor, apply, binary, ensure_tensor, unary

# ----------------------------------------------------------------- elementwise
add = binary("add", jnp.add)
subtract = binary("subtract", jnp.subtract)
multiply = binary("multiply", jnp.multiply)
divide = binary("divide", lambda x, y: jnp.true_divide(x, y))
floor_divide = binary("floor_divide", jnp.floor_divide)
mod = binary("mod", jnp.mod)
remainder = mod
floor_mod = mod
def pow_(x, y, name=None):
    # Keep python-scalar exponents static: XLA lowers integer powers to
    # multiply chains instead of exp(y*log(x)).
    if isinstance(y, (int, float)) and not isinstance(y, bool):
        x = ensure_tensor(x)
        return apply("pow", lambda v: jnp.power(v, y), x)
    x = ensure_tensor(x, ref=y if isinstance(y, Tensor) else None)
    y = ensure_tensor(y, ref=x)
    return apply("pow", jnp.power, x, y)
maximum = binary("maximum", jnp.maximum)
minimum = binary("minimum", jnp.minimum)
fmax = binary("fmax", jnp.fmax)
fmin = binary("fmin", jnp.fmin)
atan2 = binary("atan2", jnp.arctan2)
hypot = binary("hypot", jnp.hypot)
logaddexp = binary("logaddexp", jnp.logaddexp)
nextafter = binary("nextafter", jnp.nextafter)
copysign = binary("copysign", jnp.copysign)
ldexp = binary("ldexp", lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)))
heaviside = binary("heaviside", jnp.heaviside)
gcd = binary("gcd", jnp.gcd)
lcm = binary("lcm", jnp.lcm)
inner = binary("inner", jnp.inner)
outer = binary("outer", lambda x, y: jnp.outer(x, y))
kron = binary("kron", jnp.kron)


def pow(x, y, name=None):  # noqa: A001 - paddle API name
    return pow_(x, y)


def divide_no_nan(x, y):
    x, y = ensure_tensor(x), ensure_tensor(y, ref=x)
    return apply(
        "divide_no_nan",
        lambda a, b: jnp.where(b == 0, jnp.zeros((), a.dtype), a / jnp.where(b == 0, 1, b)),
        x,
        y,
    )


# --------------------------------------------------------------------- unary
exp = unary("exp", jnp.exp)
expm1 = unary("expm1", jnp.expm1)
log = unary("log", jnp.log)
log2 = unary("log2", jnp.log2)
log10 = unary("log10", jnp.log10)
log1p = unary("log1p", jnp.log1p)
sqrt = unary("sqrt", jnp.sqrt)
rsqrt = unary("rsqrt", jax.lax.rsqrt)
square = unary("square", jnp.square)
abs = unary("abs", jnp.abs)  # noqa: A001
sign = unary("sign", jnp.sign)
sgn = unary("sgn", lambda v: jnp.sign(v) if not jnp.issubdtype(v.dtype, jnp.complexfloating) else jnp.where(v == 0, 0, v / jnp.abs(v)))
ceil = unary("ceil", jnp.ceil)
floor = unary("floor", jnp.floor)
round = unary("round", jnp.round)  # noqa: A001
trunc = unary("trunc", jnp.trunc)
frac = unary("frac", lambda v: v - jnp.trunc(v))
reciprocal = unary("reciprocal", lambda v: 1.0 / v)
neg = unary("neg", jnp.negative)
sin = unary("sin", jnp.sin)
cos = unary("cos", jnp.cos)
tan = unary("tan", jnp.tan)
asin = unary("asin", jnp.arcsin)
acos = unary("acos", jnp.arccos)
atan = unary("atan", jnp.arctan)
sinh = unary("sinh", jnp.sinh)
cosh = unary("cosh", jnp.cosh)
tanh = unary("tanh", jnp.tanh)
asinh = unary("asinh", jnp.arcsinh)
acosh = unary("acosh", jnp.arccosh)
atanh = unary("atanh", jnp.arctanh)
erf = unary("erf", jax.scipy.special.erf)
erfinv = unary("erfinv", jax.scipy.special.erfinv)
sigmoid = unary("sigmoid", jax.nn.sigmoid)
logit = unary("logit", lambda v: jnp.log(v / (1.0 - v)))
digamma = unary("digamma", jax.scipy.special.digamma)
lgamma = unary("lgamma", jax.scipy.special.gammaln)
gamma = unary("gamma", lambda v: jnp.exp(jax.scipy.special.gammaln(v)) * jnp.sign(jnp.ones_like(v)))
i0 = unary("i0", jax.scipy.special.i0)
i0e = unary("i0e", jax.scipy.special.i0e)
i1 = unary("i1", jax.scipy.special.i1)
i1e = unary("i1e", jax.scipy.special.i1e)
deg2rad = unary("deg2rad", jnp.deg2rad)
rad2deg = unary("rad2deg", jnp.rad2deg)
angle = unary("angle", jnp.angle)
conj = unary("conj", jnp.conj)
real = unary("real", jnp.real)
imag = unary("imag", jnp.imag)
exponent = unary("exponent", lambda v: jnp.frexp(v)[1].astype(v.dtype))


def polygamma(x, n, name=None):
    x = ensure_tensor(x)
    return apply("polygamma", lambda v: jax.scipy.special.polygamma(n, v), x)


def multigammaln(x, p, name=None):
    x = ensure_tensor(x)
    return apply("multigammaln", lambda v: jax.scipy.special.multigammaln(v, p), x)


def clip(x, min=None, max=None, name=None):  # noqa: A002
    x = ensure_tensor(x)
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply("clip", lambda v: jnp.clip(v, lo, hi), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = ensure_tensor(x)
    s = scale.item() if isinstance(scale, Tensor) else scale

    def _scale(v):
        s_ = jnp.asarray(s, v.dtype)
        b_ = jnp.asarray(bias, v.dtype)
        out = v * s_ + b_ if bias_after_scale else (v + b_) * s_
        return out

    return apply("scale", _scale, x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    x = ensure_tensor(x)
    return apply("stanh", lambda v: scale_b * jnp.tanh(scale_a * v), x)


def increment(x, value=1.0, name=None):
    x = ensure_tensor(x)
    out = apply("increment", lambda v: v + jnp.asarray(value, v.dtype), x)
    x._bind(out._value)
    return x


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    x = ensure_tensor(x)
    return apply("nan_to_num", lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x)


# ---------------------------------------------------------------- reductions
def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy().tolist()
        return tuple(a) if isinstance(a, list) else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduction(name, jfn):
    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        x = ensure_tensor(x)
        ax = _axis_arg(axis)
        kw = {}
        if dtype is not None:
            kw["dtype"] = to_jax_dtype(dtype)
        return apply(name, lambda v: jfn(v, axis=ax, keepdims=keepdim, **kw), x)

    op.__name__ = name
    return op


sum = _reduction("sum", jnp.sum)  # noqa: A001
nansum = _reduction("nansum", jnp.nansum)
mean = _reduction("mean", jnp.mean)
nanmean = _reduction("nanmean", jnp.nanmean)
prod = _reduction("prod", jnp.prod)
max = _reduction("max", jnp.max)  # noqa: A001
min = _reduction("min", jnp.min)  # noqa: A001
amax = _reduction("amax", jnp.max)
amin = _reduction("amin", jnp.min)
all = _reduction("all", jnp.all)  # noqa: A001
any = _reduction("any", jnp.any)  # noqa: A001


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return apply(
        "count_nonzero", lambda v: jnp.count_nonzero(v, axis=ax, keepdims=keepdim).astype(jnp.int32), x
    )


def logsumexp(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _axis_arg(axis)
    return apply("logsumexp", lambda v: jax.scipy.special.logsumexp(v, axis=ax, keepdims=keepdim), x)


# ------------------------------------------------------------------ cumulative
def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)

    def _cs(v):
        if axis is None:
            v = v.reshape(-1)
            return jnp.cumsum(v, dtype=to_jax_dtype(dtype))
        return jnp.cumsum(v, axis=int(axis), dtype=to_jax_dtype(dtype))

    return apply("cumsum", _cs, x)


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return apply("cumprod", lambda v: jnp.cumprod(v, axis=int(dim), dtype=to_jax_dtype(dtype)), x)


def cummax(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)

    def _cm(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        out = jax.lax.cummax(vv, axis=ax)
        idx = jnp.asarray(jnp.argmax(jnp.cumsum(jnp.zeros_like(vv, jnp.int32), axis=ax), axis=ax))
        # indices: positions where a new max was set
        n = vv.shape[ax]
        ar = jnp.arange(n).reshape([-1 if i == ax else 1 for i in range(vv.ndim)])
        is_new = vv == out
        idx = jax.lax.cummax(jnp.where(is_new, ar, -1), axis=ax)
        return out, idx.astype(to_jax_dtype(dtype))

    return apply("cummax", _cm, x)


def cummin(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)

    def _cm(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        out = jax.lax.cummin(vv, axis=ax)
        n = vv.shape[ax]
        ar = jnp.arange(n).reshape([-1 if i == ax else 1 for i in range(vv.ndim)])
        is_new = vv == out
        idx = jax.lax.cummax(jnp.where(is_new, ar, -1), axis=ax)
        return out, idx.astype(to_jax_dtype(dtype))

    return apply("cummin", _cm, x)


def logcumsumexp(x, axis=None, name=None):
    x = ensure_tensor(x)

    def _lcse(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        return jax.lax.cumlogsumexp(vv, axis=ax)

    return apply("logcumsumexp", _lcse, x)


# ----------------------------------------------------------------- lin-adjacent
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    """Batched matmul lowering straight to dot_general (MXU path)."""
    x, y = ensure_tensor(x), ensure_tensor(y, ref=x)

    def _mm(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return apply("matmul", _mm, x, y)


def dot(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y, ref=x)
    return apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


def mv(x, vec, name=None):
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    input, x, y = ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)
    return apply(
        "addmm", lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y
    )


def multiplex(inputs, index, name=None):
    tensors = [ensure_tensor(t) for t in inputs]
    index = ensure_tensor(index)

    def _mx(idx, *vs):
        stacked = jnp.stack(vs, axis=0)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]

    return apply("multiplex", _mx, index, *tensors)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    x = ensure_tensor(x)
    return apply("trace", lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    x = ensure_tensor(x)
    return apply("diagonal", lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2), x)


# ----------------------------------------------------------------------- misc
def isfinite(x, name=None):
    return apply("isfinite", jnp.isfinite, ensure_tensor(x))


def isinf(x, name=None):
    return apply("isinf", jnp.isinf, ensure_tensor(x))


def isnan(x, name=None):
    return apply("isnan", jnp.isnan, ensure_tensor(x))


def isneginf(x, name=None):
    return apply("isneginf", jnp.isneginf, ensure_tensor(x))


def isposinf(x, name=None):
    return apply("isposinf", jnp.isposinf, ensure_tensor(x))


def isreal(x, name=None):
    return apply("isreal", jnp.isreal, ensure_tensor(x))


def broadcast_shape(x_shape, y_shape):
    import numpy as np

    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def lerp(x, y, weight, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(weight, Tensor):
        return apply("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)
    return apply("lerp", lambda a, b: a + weight * (b - a), x, y)


def rot90(x, k=1, axes=(0, 1), name=None):
    x = ensure_tensor(x)
    return apply("rot90", lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), x)


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    input = ensure_tensor(input)
    v = input._value
    lo, hi = (float(jnp.min(v)), float(jnp.max(v))) if min == 0 and max == 0 else (min, max)
    hist, _ = jnp.histogram(v.reshape(-1), bins=bins, range=(lo, hi))
    return Tensor(hist.astype(jnp.int32))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    x = ensure_tensor(x)
    args = [x] + ([ensure_tensor(weights)] if weights is not None else [])

    def _hdd(v, *w):
        h, edges = jnp.histogramdd(
            v, bins=bins, range=ranges, density=density,
            weights=w[0] if w else None,
        )
        return (h, *edges)

    out = apply("histogramdd", _hdd, *args)
    return out[0], list(out[1:])


def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)
    from paddle_tpu.tensor._ops_common import reject_tracers

    reject_tracers(
        "bincount",
        "The output length is max(x)+1 (data-dependent); under jit use "
        "paddle.scatter/segment ops with a static length.",
        x,
    )
    v = x._value
    length = int(jnp.maximum(jnp.max(v) + 1 if v.size else 0, minlength))
    w = ensure_tensor(weights)._value if weights is not None else None
    return Tensor(jnp.bincount(v.reshape(-1), weights=w, length=length))


def cartesian_prod(x, name=None):
    tensors = [ensure_tensor(t)._value for t in x]
    grids = jnp.meshgrid(*tensors, indexing="ij")
    return Tensor(jnp.stack([g.reshape(-1) for g in grids], axis=-1))


def take(x, index, mode="raise", name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    m = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return apply("take", lambda v, i: jnp.take(v.reshape(-1), i, mode=m), x, index)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)
    if x is not None:
        x = ensure_tensor(x)
        return apply("trapezoid", lambda yy, xx: jnp.trapezoid(yy, xx, axis=axis), y, x)
    return apply("trapezoid", lambda yy: jnp.trapezoid(yy, dx=dx or 1.0, axis=axis), y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)

    def _ct(yy, xx=None):
        import jax.numpy as jn

        d = jn.diff(xx, axis=axis) if xx is not None else (dx or 1.0)
        sl1 = [slice(None)] * yy.ndim
        sl2 = [slice(None)] * yy.ndim
        sl1[axis] = slice(1, None)
        sl2[axis] = slice(None, -1)
        avg = (yy[tuple(sl1)] + yy[tuple(sl2)]) / 2.0
        return jn.cumsum(avg * d, axis=axis)

    if x is not None:
        return apply("cumulative_trapezoid", _ct, y, ensure_tensor(x))
    return apply("cumulative_trapezoid", lambda yy: _ct(yy), y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = ensure_tensor(x)
    extra = []
    if prepend is not None:
        extra.append(ensure_tensor(prepend))
    if append is not None:
        extra.append(ensure_tensor(append))

    def _diff(v, *rest):
        it = iter(rest)
        pre = next(it) if prepend is not None else None
        app = next(it) if append is not None else None
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)

    return apply("diff", _diff, x, *extra)


def renorm(x, p, axis, max_norm, name=None):
    x = ensure_tensor(x)

    def _renorm(v):
        dims = [d for d in range(v.ndim) if d != axis % v.ndim]
        norms = jnp.sum(jnp.abs(v) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor

    return apply("renorm", _renorm, x)


def vander(x, n=None, increasing=False, name=None):
    x = ensure_tensor(x)
    return apply("vander", lambda v: jnp.vander(v, N=n, increasing=increasing), x)


def frexp(x, name=None):
    x = ensure_tensor(x)
    return apply("frexp", lambda v: tuple(jnp.frexp(v)), x)


def signbit(x, name=None):
    return apply("signbit", jnp.signbit, ensure_tensor(x))


def combinations(x, r=2, with_replacement=False, name=None):
    """Traceable: index combinations are static in len(x), values gathered."""
    import itertools

    import numpy as np

    x = ensure_tensor(x)
    n = int(x._value.shape[0])
    rng = range(n)
    it = itertools.combinations_with_replacement(rng, r) if with_replacement else itertools.combinations(rng, r)
    idx = np.asarray(list(it), np.int32).reshape(-1, r)
    if idx.size == 0:
        return Tensor(jnp.zeros((0, r), x._value.dtype))
    return apply("combinations", lambda v: jnp.take(v, jnp.asarray(idx), axis=0), x)


def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (reference: paddle.add_n)."""
    if isinstance(inputs, (list, tuple)):
        ts = [ensure_tensor(v) for v in inputs]
    else:
        ts = [ensure_tensor(inputs)]

    def _fn(*vals):
        out = vals[0]
        for v in vals[1:]:
            out = out + v
        return out

    return apply("add_n", _fn, *ts)


def pdist(x, p=2.0, name=None):
    """Condensed pairwise p-norm distances of an (N, M) matrix: the upper
    triangle (i < j) flattened to shape (N*(N-1)/2,)."""
    x = ensure_tensor(x)
    n = x.shape[0]
    iu, ju = np.triu_indices(n, k=1)
    pf = float(p)

    def _fn(v):
        a = v[iu.astype(np.int32)]
        b = v[ju.astype(np.int32)]
        diff = jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))
        if pf == float("inf"):
            d = jnp.max(diff, axis=-1)
        elif pf == 0.0:
            d = jnp.sum((diff != 0).astype(jnp.float32), axis=-1)
        elif pf == 2.0:
            d = jnp.sqrt(jnp.sum(diff * diff, axis=-1))
        else:
            d = jnp.sum(diff**pf, axis=-1) ** (1.0 / pf)
        return d.astype(v.dtype)

    return apply("pdist", _fn, x)
