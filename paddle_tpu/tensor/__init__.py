"""Tensor op namespace + method patching.

Mirrors the reference's monkey-patch of tensor methods
(python/paddle/base/dygraph/math_op_patch.py, tensor method table in
python/paddle/tensor/__init__.py) — every functional op is also a Tensor
method, and Python operators route through the tape-aware ops.
"""

from __future__ import annotations

from paddle_tpu._core.tensor import Tensor

from . import attribute, creation, einsum as einsum_mod, linalg, logic, manipulation, math, random, search, stat
from .creation import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .attribute import is_complex, is_floating_point, is_integer  # noqa: F401

_METHOD_SOURCES = [math, manipulation, logic, linalg, search, stat, creation, random, attribute]

# Functions that are not tensor methods (creation-style or multi-tensor entry points).
_NON_METHODS = {
    "to_tensor", "zeros", "ones", "full", "empty", "arange", "linspace", "logspace",
    "eye", "meshgrid", "tril_indices", "triu_indices", "assign",
    "uniform", "rand", "randn", "randint", "randperm", "gaussian", "normal",
    "standard_normal", "standard_gamma", "log_normal", "rayleigh",
    "broadcast_shape", "cartesian_prod", "one_hot", "scatter_nd",
    "hstack", "vstack", "dstack", "row_stack", "column_stack",
    "broadcast_tensors", "multi_dot", "multiplex",
}


def _patch_methods():
    for mod in _METHOD_SOURCES:
        for name in dir(mod):
            if name.startswith("_") or name in _NON_METHODS:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            if getattr(fn, "__module__", "").startswith("jax"):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # Non-colliding aliases and special names.
    Tensor.astype = manipulation.cast
    Tensor.cast = manipulation.cast
    Tensor.dim = lambda self: self.ndim
    Tensor.nelement = lambda self: self.size
    Tensor.element_size = lambda self: self.dtype.itemsize
    # concat/stack-style ops as methods operate with self as first element of list
    Tensor.split = lambda self, *a, **k: manipulation.split(self, *a, **k)
    Tensor.chunk = lambda self, *a, **k: manipulation.chunk(self, *a, **k)


def _patch_operators():
    from .math import _pow_impl, add, divide, floor_divide, matmul, maximum, minimum, mod, multiply, subtract
    from .logic import (
        equal,
        greater_equal,
        greater_than,
        less_equal,
        less_than,
        logical_and,
        logical_not,
        logical_or,
        logical_xor,
        not_equal,
    )

    Tensor.__add__ = lambda s, o: add(s, o)
    Tensor.__radd__ = lambda s, o: add(o, s)
    Tensor.__sub__ = lambda s, o: subtract(s, o)
    Tensor.__rsub__ = lambda s, o: subtract(o, s)
    Tensor.__mul__ = lambda s, o: multiply(s, o)
    Tensor.__rmul__ = lambda s, o: multiply(o, s)
    Tensor.__truediv__ = lambda s, o: divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: divide(o, s)
    Tensor.__floordiv__ = lambda s, o: floor_divide(s, o)
    Tensor.__rfloordiv__ = lambda s, o: floor_divide(o, s)
    Tensor.__mod__ = lambda s, o: mod(s, o)
    Tensor.__rmod__ = lambda s, o: mod(o, s)
    Tensor.__pow__ = lambda s, o: _pow_impl(s, o)
    Tensor.__rpow__ = lambda s, o: _pow_impl(o, s)
    Tensor.__matmul__ = lambda s, o: matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: matmul(o, s)
    Tensor.__neg__ = lambda s: multiply(s, -1)
    Tensor.__abs__ = lambda s: math.abs(s)
    Tensor.__pos__ = lambda s: s
    Tensor.__invert__ = lambda s: logical_not(s) if s.dtype == "bool" else math.multiply(s, 1).bitwise_not()
    Tensor.__eq__ = lambda s, o: equal(s, o)
    Tensor.__ne__ = lambda s, o: not_equal(s, o)
    Tensor.__lt__ = lambda s, o: less_than(s, o)
    Tensor.__le__ = lambda s, o: less_equal(s, o)
    Tensor.__gt__ = lambda s, o: greater_than(s, o)
    Tensor.__ge__ = lambda s, o: greater_equal(s, o)
    Tensor.__and__ = lambda s, o: logical_and(s, o) if s.dtype == "bool" else logic.bitwise_and(s, o)
    Tensor.__or__ = lambda s, o: logical_or(s, o) if s.dtype == "bool" else logic.bitwise_or(s, o)
    Tensor.__xor__ = lambda s, o: logical_xor(s, o) if s.dtype == "bool" else logic.bitwise_xor(s, o)


_patch_methods()
_patch_operators()

from .array import array_length, array_read, array_write, create_array  # noqa: F401,E402

# signal-domain tensor methods (reference tensor_method_func includes stft/istft)
from paddle_tpu import signal as _signal  # noqa: E402

Tensor.stft = _signal.stft
Tensor.istft = _signal.istft
stft = _signal.stft
istft = _signal.istft

# reference tensor_method_func attaches even multi-tensor/creation entry
# points as methods (self = first argument); match that surface exactly
from .manipulation import broadcast_tensors as _bt  # noqa: E402
from .linalg import multi_dot as _md  # noqa: E402

Tensor.broadcast_shape = lambda self, y: math.broadcast_shape(self.shape, y.shape if isinstance(y, Tensor) else y)
Tensor.broadcast_tensors = lambda self, *o: _bt([self, *o])
Tensor.multi_dot = lambda self, *o: _md([self, *(o[0] if len(o) == 1 and isinstance(o[0], (list, tuple)) else o)])
Tensor.multiplex = lambda self, index: math.multiplex(self, index)
Tensor.scatter_nd = lambda self, updates, shape: manipulation.scatter_nd(self, updates, shape)
Tensor.create_parameter = staticmethod(lambda *a, **k: __import__("paddle_tpu.framework.defaults", fromlist=["create_parameter"]).create_parameter(*a, **k))

# generated in-place op tier (framework/op_registry codegen)
from paddle_tpu.framework.op_registry import generate_inplace_variants as _gen_inplace  # noqa: E402
_gen_inplace()

# surface the generated `op_` names (and any hand-written ones the star
# imports above predate) on the package so `paddle.cos_` etc. resolve
for _mod in _METHOD_SOURCES:
    for _n in dir(_mod):
        if _n.endswith("_") and not _n.startswith("_") and _n not in globals():
            globals()[_n] = getattr(_mod, _n)
del _mod, _n
