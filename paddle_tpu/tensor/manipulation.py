"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py, ~98 fns).

XLA has no strides — every view op here is a functional (often zero-copy after
XLA layout assignment) transform.  In-place variants rebind the wrapper's
payload, matching the reference's inplace-op semantics without aliasing."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core.dtype import to_jax_dtype
from ._ops_common import Tensor, apply, ensure_tensor


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._value)]
    return [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]


def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    sh = _shape_list(shape)
    return apply("reshape", lambda v: jnp.reshape(v, sh), x)


def reshape_(x, shape, name=None):
    from ._ops_common import inplace_from

    return inplace_from(x, reshape, shape)


def transpose(x, perm, name=None):
    x = ensure_tensor(x)
    p = [int(i) for i in perm]
    return apply("transpose", lambda v: jnp.transpose(v, p), x)


def moveaxis(x, source, destination, name=None):
    x = ensure_tensor(x)
    return apply("moveaxis", lambda v: jnp.moveaxis(v, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    x = ensure_tensor(x)
    return apply("swapaxes", lambda v: jnp.swapaxes(v, axis0, axis1), x)


def transpose_(x, perm, name=None):
    """In-place transpose (reference transpose_): rebinds x to the permuted
    buffer via the shared in-place helper."""
    from paddle_tpu.tensor._ops_common import inplace_from

    return inplace_from(x, transpose, perm)
t = lambda x, name=None: transpose(ensure_tensor(x), list(range(ensure_tensor(x).ndim))[::-1])  # noqa: E731


def concat(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply("concat", lambda *vs: jnp.concatenate(vs, axis=ax), *tensors)


def stack(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return apply("stack", lambda *vs: jnp.stack(vs, axis=int(axis)), *tensors)


def unstack(x, axis=0, num=None, name=None):
    x = ensure_tensor(x)
    n = num if num is not None else x.shape[axis]
    outs = apply(
        "unstack",
        lambda v: tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis)),
        x,
    )
    return list(outs)


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    if isinstance(num_or_sections, int):
        outs = apply(
            "split", lambda v: tuple(jnp.split(v, num_or_sections, axis=ax)), x
        )
    else:
        secs = [int(s) for s in num_or_sections]
        total = x.shape[ax]
        if any(s == -1 for s in secs):
            known = sum(s for s in secs if s != -1)
            secs = [total - known if s == -1 else s for s in secs]
        idx = np.cumsum(secs)[:-1].tolist()
        outs = apply("split", lambda v: tuple(jnp.split(v, idx, axis=ax)), x)
    return list(outs)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def vsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=0)


def hsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=1 if ensure_tensor(x).ndim > 1 else 0)


def dsplit(x, num_or_indices, name=None):
    return split(x, num_or_indices, axis=2)


def unbind(input, axis=0, name=None):
    return unstack(input, axis)


def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        ax = None
    else:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        ax = tuple(int(a) for a in axes if x.shape[int(a)] == 1)
    return apply("squeeze", lambda v: jnp.squeeze(v, axis=ax), x)


def squeeze_(x, axis=None, name=None):
    from ._ops_common import inplace_from

    return inplace_from(x, squeeze, axis)


def unsqueeze(x, axis, name=None):
    x = ensure_tensor(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a.item()) if isinstance(a, Tensor) else int(a) for a in axes]

    def _unsq(v):
        for a in sorted(axes):
            v = jnp.expand_dims(v, a if a >= 0 else a + v.ndim + 1)
        return v

    return apply("unsqueeze", _unsq, x)


def unsqueeze_(x, axis, name=None):
    from ._ops_common import inplace_from

    return inplace_from(x, unsqueeze, axis)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0

    def _fl(v):
        if v.ndim == 0:
            return v.reshape(1)
        new_shape = list(v.shape[:s]) + [-1] + list(v.shape[e + 1 :])
        return v.reshape(new_shape)

    return apply("flatten", _fl, x)


def flip(x, axis, name=None):
    x = ensure_tensor(x)
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply("flip", lambda v: jnp.flip(v, axis=tuple(int(a) for a in axes)), x)


def fliplr(x, name=None):
    return flip(x, 1)


def flipud(x, name=None):
    return flip(x, 0)


def roll(x, shifts, axis=None, name=None):
    x = ensure_tensor(x)
    sh = tuple(shifts) if isinstance(shifts, (list, tuple)) else int(shifts)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply("roll", lambda v: jnp.roll(v, sh, axis=ax), x)


def tile(x, repeat_times, name=None):
    x = ensure_tensor(x)
    reps = _shape_list(repeat_times)
    return apply("tile", lambda v: jnp.tile(v, reps), x)


def expand(x, shape, name=None):
    x = ensure_tensor(x)
    sh = _shape_list(shape)
    cur = list(x.shape)
    full = list(sh)
    # -1 entries keep original dims (right aligned)
    offset = len(full) - len(cur)
    for i in range(len(full)):
        if full[i] == -1:
            full[i] = cur[i - offset] if i >= offset else 1
    return apply("expand", lambda v: jnp.broadcast_to(v, full), x)


def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


def broadcast_to(x, shape, name=None):
    x = ensure_tensor(x)
    return apply("broadcast_to", lambda v: jnp.broadcast_to(v, _shape_list(shape)), x)


def broadcast_tensors(input, name=None):
    tensors = [ensure_tensor(t) for t in input]
    return list(apply("broadcast_tensors", lambda *vs: tuple(jnp.broadcast_arrays(*vs)), *tensors))


def cast(x, dtype):
    x = ensure_tensor(x)
    dt = to_jax_dtype(dtype)
    return apply("cast", lambda v: v.astype(dt), x)


def cast_(x, dtype):
    from ._ops_common import inplace_from

    return inplace_from(x, cast, dtype)


astype = cast


def slice(input, axes, starts, ends):  # noqa: A001
    input = ensure_tensor(input)
    axes = [int(a) for a in axes]
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]

    def _do(v):
        sl = [None] * v.ndim
        for d in range(v.ndim):
            sl[d] = (0, v.shape[d], 1)
        for a, s, e in zip(axes, starts, ends):
            n = v.shape[a]
            s2 = s + n if s < 0 else s
            e2 = e + n if e < 0 else e
            e2 = min(e2, n)
            sl[a] = (s2, e2, 1)
        indexer = tuple(jnp.s_[b:e:st] for (b, e, st) in sl)
        return v[indexer]

    return apply("slice", _do, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)

    def _do(v):
        sl = [jnp.s_[:]] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            sl[int(a)] = jnp.s_[int(s) : int(e) : int(st)]
        return v[tuple(sl)]

    return apply("strided_slice", _do, x)


def gather(x, index, axis=0, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply("gather", lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i, axis=ax), x, index)


def gather_nd(x, index, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)

    def _gnd(v, idx):
        k = idx.shape[-1]
        out = v[tuple(jnp.moveaxis(idx, -1, 0))]
        return out

    return apply("gather_nd", _gnd, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)

    def _sc(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        z = v.at[i].set(jnp.zeros_like(u))
        return z.at[i].add(u)

    return apply("scatter", _sc, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    from ._ops_common import inplace_from

    return inplace_from(x, scatter, index, updates, overwrite)


def scatter_nd(index, updates, shape, name=None):
    index, updates = ensure_tensor(index), ensure_tensor(updates)
    sh = _shape_list(shape)
    return apply(
        "scatter_nd",
        lambda i, u: jnp.zeros(sh, u.dtype).at[tuple(jnp.moveaxis(i, -1, 0))].add(u),
        index,
        updates,
    )


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)
    return apply(
        "scatter_nd_add",
        lambda v, i, u: v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u),
        x,
        index,
        updates,
    )


def index_select(x, index, axis=0, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    return apply("index_select", lambda v, i: jnp.take(v, i, axis=int(axis)), x, index)


def index_sample(x, index):
    x, index = ensure_tensor(x), ensure_tensor(index)
    return apply(
        "index_sample",
        lambda v, i: jnp.take_along_axis(v, i, axis=1),
        x,
        index,
    )


def index_add(x, index, axis, value, name=None):
    x, index, value = ensure_tensor(x), ensure_tensor(index), ensure_tensor(value)

    def _ia(v, i, u):
        vm = jnp.moveaxis(v, axis, 0)
        um = jnp.moveaxis(u, axis, 0)
        out = vm.at[i].add(um)
        return jnp.moveaxis(out, 0, axis)

    return apply("index_add", _ia, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    x = ensure_tensor(x)
    value = ensure_tensor(value)
    idx_tensors = [ensure_tensor(i) for i in indices]

    def _ip(v, u, *idxs):
        if accumulate:
            return v.at[tuple(idxs)].add(u)
        return v.at[tuple(idxs)].set(u)

    return apply("index_put", _ip, x, value, *idx_tensors)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    return apply(
        "take_along_axis", lambda v, i: jnp.take_along_axis(v, i, axis=axis), arr, indices
    )


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True, name=None):  # noqa: A002
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    values = ensure_tensor(values)

    def _pa(v, i, u):
        u = jnp.broadcast_to(u, i.shape) if u.ndim < i.ndim or u.shape != i.shape else u
        if reduce == "assign":
            return jnp.put_along_axis(v, i, u, axis=axis, inplace=False)
        vm = jnp.moveaxis(v, axis, 0)
        im = jnp.moveaxis(i, axis, 0)
        um = jnp.moveaxis(u, axis, 0)
        # scatter per position along other dims using at[] with explicit index grids
        grids = jnp.meshgrid(*[jnp.arange(s) for s in im.shape], indexing="ij")
        full_idx = list(grids)
        full_idx[0] = im
        if reduce in ("add", "sum"):
            out = vm.at[tuple(full_idx)].add(um)
        elif reduce in ("mul", "multiply"):
            out = vm.at[tuple(full_idx)].multiply(um)
        elif reduce == "amax":
            out = vm.at[tuple(full_idx)].max(um)
        elif reduce == "amin":
            out = vm.at[tuple(full_idx)].min(um)
        elif reduce == "mean":
            ones = jnp.ones_like(um)
            cnt = jnp.zeros_like(vm).at[tuple(full_idx)].add(ones)
            tot = vm.at[tuple(full_idx)].add(um)
            out = jnp.where(cnt > 0, tot / jnp.maximum(cnt + include_self, 1), vm)
        else:
            raise ValueError(f"unknown reduce {reduce}")
        return jnp.moveaxis(out, 0, axis)

    return apply("put_along_axis", _pa, arr, indices, values)


def masked_select(x, mask, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    from paddle_tpu.tensor._ops_common import reject_tracers

    reject_tracers(
        "masked_select",
        "Use paddle.where / masked_fill (static shape) or move the select "
        "outside the compiled region.",
        x, mask,
    )
    shape = jnp.broadcast_shapes(x._value.shape, mask._value.shape)
    v = jnp.broadcast_to(x._value, shape)
    m = jnp.broadcast_to(mask._value, shape)
    return Tensor(v[m])  # concrete boolean index: stays on device


def masked_fill(x, mask, value, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    val = value._value if isinstance(value, Tensor) else value
    return apply("masked_fill", lambda v, m: jnp.where(m, jnp.asarray(val, v.dtype), v), x, mask)


def masked_fill_(x, mask, value, name=None):
    from ._ops_common import inplace_from

    return inplace_from(x, masked_fill, mask, value)


def masked_scatter(x, mask, value, name=None):
    """Fill masked positions of x with consecutive values (traceable:
    cumsum+gather keeps the output shape static — the round-1 numpy
    implementation broke under jit)."""
    x, mask, value = ensure_tensor(x), ensure_tensor(mask), ensure_tensor(value)
    if not any(
        isinstance(t._value, jax.core.Tracer) for t in (x, mask, value)
    ):
        needed = int(jnp.sum(jnp.broadcast_to(mask._value, x._value.shape)))
        if int(value._value.size) < needed:
            raise ValueError(
                f"masked_scatter: value has {int(value._value.size)} elements "
                f"but mask selects {needed}"
            )

    def _ms(v, m, vals):
        mb = jnp.broadcast_to(m, v.shape).reshape(-1)
        flat = v.reshape(-1)
        vflat = vals.reshape(-1)
        # k-th True position reads vals[k]
        pos = jnp.cumsum(mb.astype(jnp.int32)) - 1
        picked = jnp.take(vflat, jnp.clip(pos, 0, vflat.shape[0] - 1))
        return jnp.where(mb, picked, flat).reshape(v.shape)

    return apply("masked_scatter", _ms, x, mask, value)


def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    if isinstance(repeats, Tensor):
        from paddle_tpu.tensor._ops_common import reject_tracers

        reject_tracers(
            "repeat_interleave",
            "A tensor `repeats` makes the output length data-dependent; use "
            "an int repeats (static) under jit.",
            repeats,
        )
        reps = repeats
        return apply(
            "repeat_interleave",
            lambda v, r: jnp.repeat(
                v.reshape(-1) if axis is None else v,
                r,
                axis=0 if axis is None else axis,
                total_repeat_length=int(np.asarray(r).sum()),
            ),
            x,
            reps,
        )
    return apply(
        "repeat_interleave",
        lambda v: jnp.repeat(v.reshape(-1) if axis is None else v, repeats, axis=0 if axis is None else axis),
        x,
    )


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None, size=None, fill_value=None):
    """Unique values (reference paddle.unique).  TPU extension beyond the
    reference: pass `size=N` (a static bound on the unique count) to make
    the op jit-traceable — outputs are padded to N with `fill_value`
    (default: the max value), the jnp.unique(size=...) contract."""
    x = ensure_tensor(x)
    if size is not None:
        if axis is not None:
            raise ValueError("unique(size=...) supports axis=None only")

        def _u(v):
            flat = v.reshape(-1)
            res = jnp.unique(
                flat, return_index=return_index, return_inverse=return_inverse,
                return_counts=return_counts, size=int(size), fill_value=fill_value,
            )
            return res if isinstance(res, tuple) else (res,)

        outs = apply("unique", _u, x)
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        return outs[0] if len(outs) == 1 else tuple(outs)
    from paddle_tpu.tensor._ops_common import reject_tracers

    reject_tracers(
        "unique",
        "The number of unique values is data-dependent; pass size=N (static "
        "bound, padded outputs) to run under jit, or run unique outside the "
        "compiled region.",
        x,
    )
    res = np.unique(
        np.asarray(x._value),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    from paddle_tpu.tensor._ops_common import reject_tracers

    reject_tracers(
        "unique_consecutive",
        "The run count is data-dependent; compare neighbors (static shape) "
        "or run it outside the compiled region.",
        x,
    )
    arr = np.asarray(x._value)
    if axis is None:
        arr = arr.reshape(-1)
        ax = 0
    else:
        ax = axis
    if arr.size == 0:
        out = [Tensor(jnp.asarray(arr))]
    else:
        keep = np.ones(arr.shape[ax], bool)
        sl = np.take(arr, np.arange(1, arr.shape[ax]), axis=ax) != np.take(arr, np.arange(arr.shape[ax] - 1), axis=ax)
        if sl.ndim > 1:
            sl = sl.any(axis=tuple(d for d in range(sl.ndim) if d != ax))
        keep[1:] = sl
        uniq = np.compress(keep, arr, axis=ax)
        out = [Tensor(jnp.asarray(uniq))]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            out.append(Tensor(jnp.asarray(inv.astype(np.int64))))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, arr.shape[ax]))
            out.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return out[0] if len(out) == 1 else tuple(out)


def as_complex(x, name=None):
    x = ensure_tensor(x)
    return apply("as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x)


def as_real(x, name=None):
    x = ensure_tensor(x)
    return apply("as_real", lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


def atleast_1d(*inputs, name=None):
    outs = [apply("atleast_1d", jnp.atleast_1d, ensure_tensor(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply("atleast_2d", jnp.atleast_2d, ensure_tensor(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply("atleast_3d", jnp.atleast_3d, ensure_tensor(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def tensordot(x, y, axes=2, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def view(x, shape_or_dtype, name=None):
    x = ensure_tensor(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    dt = to_jax_dtype(shape_or_dtype)
    return apply("view_dtype", lambda v: jax.lax.bitcast_convert_type(v, dt), x)


def view_as(x, other, name=None):
    return reshape(x, ensure_tensor(other).shape)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view as a gather (XLA has no strides — SURVEY.md §7 hard
    parts; the gather formulation is traceable and differentiable)."""
    x = ensure_tensor(x)
    shape = [int(s) for s in shape]
    stride = [int(s) for s in stride]

    def _as_strided(v):
        flat = v.reshape(-1)
        idx = jnp.asarray(offset, jnp.int32)
        for dim, (n, st) in enumerate(zip(shape, stride)):
            ax_idx = jax.lax.broadcasted_iota(jnp.int32, tuple(shape), dim)
            idx = idx + ax_idx * jnp.int32(st)
        return jnp.take(flat, idx)

    return apply("as_strided", _as_strided, x)


def unfold(x, axis, size, step, name=None):
    x = ensure_tensor(x)

    def _unfold(v):
        n = v.shape[axis]
        starts = jnp.arange(0, n - size + 1, step)
        idx = starts[:, None] + jnp.arange(size)[None, :]
        vm = jnp.moveaxis(v, axis, 0)
        out = vm[idx]  # (n_windows, size, ...)
        out = jnp.moveaxis(out, (0, 1), (axis, v.ndim))
        return out

    return apply("unfold", _unfold, x)


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = ensure_tensor(x)
    if isinstance(num_or_indices, int):
        return list(
            apply("tensor_split", lambda v: tuple(jnp.array_split(v, num_or_indices, axis=axis)), x)
        )
    return list(
        apply("tensor_split", lambda v: tuple(jnp.split(v, list(num_or_indices), axis=axis)), x)
    )


def hstack(x, name=None):
    return apply("hstack", lambda *vs: jnp.hstack(vs), *[ensure_tensor(t) for t in x])


def vstack(x, name=None):
    return apply("vstack", lambda *vs: jnp.vstack(vs), *[ensure_tensor(t) for t in x])


def dstack(x, name=None):
    return apply("dstack", lambda *vs: jnp.dstack(vs), *[ensure_tensor(t) for t in x])


def row_stack(x, name=None):
    return vstack(x)


def column_stack(x, name=None):
    return apply("column_stack", lambda *vs: jnp.column_stack(vs), *[ensure_tensor(t) for t in x])


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    input = ensure_tensor(input)
    shard_size = (index_num + nshards - 1) // nshards

    def _si(v):
        in_shard = (v // shard_size) == shard_id
        return jnp.where(in_shard, v % shard_size, ignore_value)

    return apply("shard_index", _si, input)


# ----------------------------------------------------------- getitem/setitem
def _norm_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_norm_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(idx)
    return idx


def _getitem(x, idx):
    nidx = _norm_index(idx)
    return apply("getitem", lambda v: v[nidx], x)


def _setitem_(x, idx, value):
    nidx = _norm_index(idx)
    value = ensure_tensor(value, ref=x)

    def _set(v, u):
        return v.at[nidx].set(u.astype(v.dtype))

    from paddle_tpu._core.autograd import is_grad_enabled

    if is_grad_enabled() and not x.stop_gradient and x._grad_node is None:
        raise RuntimeError(
            "in-place __setitem__ on a leaf Tensor that requires grad would "
            "lose its gradient; use paddle.no_grad() or the functional "
            "put_along_axis/scatter"
        )
    alias = Tensor(x._value, stop_gradient=x.stop_gradient)
    alias._grad_node, alias._out_index = x._grad_node, x._out_index
    out = apply("setitem", _set, alias, value)
    x._bind(out._value)
    x._grad_node, x._out_index = out._grad_node, out._out_index
    return x


def fill_(x, value):
    x = ensure_tensor(x)
    x._bind(jnp.full_like(x._value, value))
    return x


def zero_(x):
    return fill_(x, 0)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    x = ensure_tensor(x)
    n = min(x.shape[-2], x.shape[-1])
    idx = jnp.arange(n - abs(offset))
    rows = idx + max(0, -offset)
    cols = idx + max(0, offset)
    x._bind(x._value.at[..., rows, cols].set(value))
    return x


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def _pad(v):
        nd = v.ndim
        if len(pad) == 2 * nd:
            # paddle full-dim format: [before0, after0, before1, after1, ...]? No:
            # paddle uses per-dim pairs in dim order for len==2*ndim
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # partial spec applies to trailing spatial dims (paddle NCHW conv style):
            # pad = [left, right, top, bottom, front, back...] applying to last dims reversed
            npairs = len(pad) // 2
            widths = [(0, 0)] * nd
            if data_format.endswith("C") and nd >= 3:  # NHWC / NLC / NDHWC
                spatial = list(range(1, nd - 1))[-npairs:]
            else:
                spatial = list(range(nd))[-npairs:]
            for j, d in enumerate(reversed(spatial)):
                widths[d] = (pad[2 * j], pad[2 * j + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, widths, mode="constant", constant_values=value)
        return jnp.pad(v, widths, mode=jmode)

    return apply("pad", _pad, x)


def crop(x, shape=None, offsets=None, name=None):
    x = ensure_tensor(x)
    sh = _shape_list(shape) if shape is not None else x.shape
    off = _shape_list(offsets) if offsets is not None else [0] * x.ndim
    sh = [x.shape[i] - off[i] if s == -1 else s for i, s in enumerate(sh)]

    def _crop(v):
        return jax.lax.dynamic_slice(v, off, sh)

    return apply("crop", _crop, x)


def numel(x, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.asarray(x.size, jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(ensure_tensor(x).ndim, jnp.int32))


def shape(x):
    return Tensor(jnp.asarray(ensure_tensor(x).shape, jnp.int32))


def unflatten(x, axis, shape, name=None):
    """Expand one axis into the given shape (reference:
    python/paddle/tensor/manipulation.py unflatten); at most one -1 entry."""
    x = ensure_tensor(x)
    ax = int(axis) % max(x.ndim, 1)
    sh = _shape_list(shape)
    neg = [i for i, s in enumerate(sh) if s == -1]
    if len(neg) > 1:
        raise ValueError("unflatten: at most one -1 in shape")
    if neg:
        known = int(np.prod([s for s in sh if s != -1])) or 1
        sh[neg[0]] = x.shape[ax] // known
    new_shape = list(x.shape[:ax]) + sh + list(x.shape[ax + 1 :])
    return apply("unflatten", lambda v: jnp.reshape(v, new_shape), x)


def reverse(x, axis, name=None):
    """Legacy alias of flip (reference: paddle.reverse)."""
    return flip(x, axis)


def index_fill(x, index, axis, value, name=None):
    """Fill slices of x at `index` positions along `axis` with scalar value."""
    x, index = ensure_tensor(x), ensure_tensor(index)
    ax = int(axis) % x.ndim
    if isinstance(value, Tensor):
        value = value._value

    def _fn(v, idx):
        hit = jnp.zeros((v.shape[ax],), jnp.bool_).at[idx].set(True)
        bshape = [1] * v.ndim
        bshape[ax] = v.shape[ax]
        return jnp.where(hit.reshape(bshape), jnp.asarray(value, v.dtype), v)

    return apply("index_fill", _fn, x, index)


def index_fill_(x, index, axis, value, name=None):
    from ._ops_common import inplace_from

    return inplace_from(x, index_fill, index, axis, value)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write y onto the (offset) diagonal of the (axis1, axis2) planes."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    a1, a2 = int(axis1) % x.ndim, int(axis2) % x.ndim
    off = int(offset)

    def _fn(v, w):
        v2 = jnp.moveaxis(v, (a1, a2), (-2, -1))
        n, m = v2.shape[-2], v2.shape[-1]
        i = jax.lax.broadcasted_iota(jnp.int32, (n, m), 0)
        j = jax.lax.broadcasted_iota(jnp.int32, (n, m), 1)
        mask = (j - i) == off
        # position along the diagonal for each (i, j) on it
        pos = jnp.where(off >= 0, i, j)
        L = w.shape[-1]
        wfull = jnp.take(w.astype(v.dtype), jnp.clip(pos, 0, L - 1), axis=-1)
        out = jnp.where(mask, wfull, v2)
        return jnp.moveaxis(out, (-2, -1), (a1, a2))

    return apply("diagonal_scatter", _fn, x, y)


def select_scatter(x, values, axis, index, name=None):
    """Write `values` into x at position `index` along `axis`."""
    x, values = ensure_tensor(x), ensure_tensor(values)
    ax = int(axis) % x.ndim
    idx = int(index)

    def _fn(v, w):
        upd = jnp.expand_dims(w.astype(v.dtype), ax)
        return jax.lax.dynamic_update_slice_in_dim(v, upd, idx, ax)

    return apply("select_scatter", _fn, x, values)


def t_(x, name=None):
    from ._ops_common import inplace_from

    return inplace_from(x, t)
