"""Reader decorators (reference: python/paddle/reader/decorator.py).

Pure-Python composable iterators over sample-yielding callables — the
pre-DataLoader data tier.  TPU-native note: `paddle.io.DataLoader` is the
performant path (thread prefetch + spawned workers over the native shm
ring); this tier exists for reference-API compatibility and light glue.
"""

from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["cache", "map_readers", "shuffle", "chain", "compose",
           "buffered", "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader):
    """Cache the first COMPLETE pass in memory; later passes replay it.
    A pass abandoned early (e.g. via firstn) does not poison the cache —
    the next pass re-reads the source from the start."""
    all_data = []
    filled = [False]

    def __impl__():
        if not filled[0]:
            fresh = []
            for d in reader():
                fresh.append(d)
                yield d
            all_data[:] = fresh  # only a finished pass becomes the cache
            filled[0] = True
        else:
            yield from all_data

    return __impl__


def map_readers(func, *readers):
    """Yield func(*samples) over readers zipped in lockstep."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: fill `buf_size`, emit in random order."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    """Concatenate readers back to back."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, b1, b2) from ((a,), (b1, b2)).
    check_alignment=True (default) raises when lengths diverge."""
    check_alignment = kwargs.pop("check_alignment", True)
    if kwargs:
        raise TypeError(f"unexpected kwargs {sorted(kwargs)}")

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        zipper = zip(*rs) if not check_alignment else itertools.zip_longest(
            *rs, fillvalue=_SENTINEL)
        for outputs in zipper:
            if check_alignment and any(o is _SENTINEL for o in outputs):
                raise ValueError("readers have different lengths")
            yield sum((make_tuple(o) for o in outputs), ())

    return reader


_SENTINEL = object()


class _Raise:
    """Exception envelope crossing a worker-thread queue: the consumer
    re-raises, so a failed source never masquerades as a short epoch."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples."""

    def data_reader():
        q = _queue.Queue(maxsize=size)

        def read_worker():
            try:
                for d in reader():
                    q.put(d)
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                q.put(_Raise(exc))
            else:
                q.put(_SENTINEL)

        t = threading.Thread(target=read_worker, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _SENTINEL:
                break
            if isinstance(e, _Raise):
                raise e.exc  # a failed source must not look like a short epoch
            yield e

    return data_reader


def firstn(reader, n):
    """Only the first n samples."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with `process_num` worker THREADS
    (reference uses threads too — the GIL is released in numpy/IO
    mappers).  order=True preserves input order."""

    def data_reader():
        in_q = _queue.Queue(buffer_size)
        out_q = _queue.Queue(buffer_size)
        done = [0]
        lock = threading.Lock()

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as exc:  # noqa: BLE001
                out_q.put(_Raise(exc))
            finally:
                for _ in range(process_num):
                    in_q.put(_SENTINEL)

        def work():
            while True:
                item = in_q.get()
                if item is _SENTINEL:
                    with lock:
                        done[0] += 1
                        if done[0] == process_num:
                            out_q.put(_SENTINEL)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as exc:  # noqa: BLE001 — a raising
                    out_q.put(_Raise(exc))  # mapper must not deadlock the
                    # consumer: keep draining so the sentinel still arrives

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        def _next_items():
            while True:
                e = out_q.get()
                if e is _SENTINEL:
                    return
                if isinstance(e, _Raise):
                    raise e.exc
                yield e

        if not order:
            for e in _next_items():
                yield e[1]
        else:
            pending = {}
            want = 0
            for e in _next_items():
                pending[e[0]] = e[1]
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            while want in pending:  # drain tail
                yield pending.pop(want)
                want += 1

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers via worker threads (reference uses
    processes; the sample producers here are Python callables whose
    numpy/IO work releases the GIL — see io.DataLoader for the true
    spawned-worker tier)."""

    def data_reader():
        q = _queue.Queue(queue_size)
        remaining = [len(readers)]
        lock = threading.Lock()

        def work(r):
            try:
                for d in r():
                    q.put(d)
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                q.put(_Raise(exc))
            finally:
                with lock:
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        q.put(_SENTINEL)

        for r in readers:
            threading.Thread(target=work, args=(r,), daemon=True).start()
        while True:
            e = q.get()
            if e is _SENTINEL:
                break
            if isinstance(e, _Raise):
                raise e.exc
            yield e

    return data_reader
