"""paddle.inference parity — the serving path.

Reference: AnalysisPredictor (paddle/fluid/inference/api/analysis_predictor.cc):
offline graph analysis + optimized execution with zero-copy IO.

TPU-native: the saved artifact IS the optimized program (StableHLO bytecode
exported AOT by paddle_tpu.static.save_inference_model — XLA did the fusion/
placement work the reference's 286 IR passes do).  `Predictor` deserializes
and executes it with no Python graph in the loop; input/output bindings are
device buffers (jax arrays), the zero-copy analog.
"""

from __future__ import annotations

import json

import numpy as np
import jax

__all__ = ["Config", "Predictor", "create_predictor"]


class Config:
    """AnalysisConfig parity (subset: model path + switches that map to XLA)."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._device = "tpu" if any(d.platform == "tpu" for d in jax.devices()) else "cpu"
        self._mesh = None
        self._input_specs = None

    def enable_use_gpu(self, *a, **k):
        pass

    def disable_gpu(self):
        self._device = "cpu"

    def set_model(self, model_path, params_path=None):
        self.model_path = model_path
        self.params_path = params_path

    def enable_tensor_parallel(self, mesh, input_specs=None):
        """Serve the loaded program GSPMD-partitioned over `mesh` (reference
        capability: analysis_predictor multi-device serving).  input_specs:
        optional list of PartitionSpec, one per program input (default
        replicated inputs; XLA still partitions the internal compute)."""
        from jax.sharding import Mesh

        self._mesh = mesh.jax_mesh if hasattr(mesh, "jax_mesh") else mesh
        if not isinstance(self._mesh, Mesh):
            raise TypeError(f"mesh must be a jax Mesh/ProcessMesh, got {type(mesh)}")
        self._input_specs = input_specs
        return self


class Predictor:
    def __init__(self, path_prefix_or_config):
        mesh = input_specs = None
        if isinstance(path_prefix_or_config, Config):
            prefix = path_prefix_or_config.model_path
            mesh = path_prefix_or_config._mesh
            input_specs = path_prefix_or_config._input_specs
        else:
            prefix = path_prefix_or_config
        if prefix.endswith(".pdmodel"):
            prefix = prefix[: -len(".pdmodel")]
        self.prefix = prefix
        with open(prefix + ".json") as f:
            self.manifest = json.load(f)
        with open(prefix + ".pdmodel", "rb") as f:
            self._exported = jax.export.deserialize(bytearray(f.read()))
        self._input_names = [s["name"] for s in self.manifest["feed"]]
        self._output_names = [s["name"] for s in self.manifest["fetch"]]
        self._inputs = {}
        self._call = self._exported.call
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            specs = input_specs or [PartitionSpec()] * len(self._input_names)
            shardings = [
                s if isinstance(s, NamedSharding)
                else NamedSharding(mesh, s if isinstance(s, PartitionSpec) else PartitionSpec(*s))
                for s in specs
            ]
            # one partitioned executable per mesh: exported.call is traceable,
            # so GSPMD partitions the whole serving program over the mesh
            self._call = jax.jit(self._exported.call, in_shardings=shardings)

    # reference-style handle API
    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name):
        pred = self

        class _Handle:
            def copy_from_cpu(self, arr):
                pred._inputs[name] = jax.numpy.asarray(arr)

            def reshape(self, shape):
                pass

        return _Handle()

    def get_output_handle(self, name):
        pred = self

        class _Handle:
            def copy_to_cpu(self):
                return np.asarray(pred._last_outputs[pred._output_names.index(name)])

        return _Handle()

    def run(self, inputs=None):
        if inputs is not None:
            vals = [jax.numpy.asarray(a) for a in inputs]
        else:
            vals = [self._inputs[n] for n in self._input_names]
        out = self._call(*vals)
        self._last_outputs = list(out) if isinstance(out, (tuple, list)) else [out]
        return [np.asarray(o) for o in self._last_outputs]

    __call__ = run


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
