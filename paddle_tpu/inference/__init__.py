"""paddle.inference parity — the serving path.

Reference: AnalysisPredictor + AnalysisConfig
(paddle/fluid/inference/api/analysis_predictor.h:100,
paddle_analysis_config.h:676 Precision modes).

TPU-native: the saved artifact IS the optimized program (StableHLO bytecode
exported AOT by paddle_tpu.static.save_inference_model — XLA did the fusion/
placement work the reference's 286 IR passes do).  `Predictor` deserializes
and executes it with no Python graph in the loop; input/output bindings are
device buffers (jax arrays), the zero-copy analog.

Precision follows the TensorRT-engine model re-done for XLA: per-precision
programs are BUILT at export (save_inference_model precision=/
extra_precisions=; bf16/fp16 cast rewrite, int8/int4 weight-only quant
pass) and SELECTED at load (Config.set_precision).  Every Config switch
either works or warns — a requested optimization is never silently dropped
(round-4 VERDICT weak #5).
"""

from __future__ import annotations

import json
import os
import time
import warnings

import numpy as np
import jax

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType"]


class PrecisionType:
    """AnalysisConfig::Precision parity (paddle_analysis_config.h)."""

    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "weight_only_int8"


def _warn_unsupported(switch, why):
    warnings.warn(
        f"inference.Config.{switch}: {why}", RuntimeWarning, stacklevel=3)


class Config:
    """AnalysisConfig parity.  Switches map to their XLA-era equivalent;
    anything with no equivalent warns instead of silently no-op'ing."""

    def __init__(self, model_path=None, params_path=None):
        self.model_path = model_path
        self.params_path = params_path
        self._device = "tpu" if any(d.platform == "tpu" for d in jax.devices()) else "cpu"
        self._mesh = None
        self._input_specs = None
        self._precision = None
        self._warmup = False
        self._profile = False

    # ------------------------------------------------------------ model/dev
    def set_model(self, model_path, params_path=None):
        self.model_path = model_path
        self.params_path = params_path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=None):
        """Reference signature; 'gpu' means 'the accelerator' here.  The
        memory-pool size is PJRT-managed (warns); precision maps to
        set_precision."""
        if memory_pool_init_size_mb != 100:
            _warn_unsupported(
                "enable_use_gpu", "memory_pool_init_size_mb is managed by "
                "PJRT; the argument is ignored")
        if device_id:
            _warn_unsupported(
                "enable_use_gpu", f"device_id={device_id} ignored: single "
                "default accelerator per process under PJRT")
        if precision is not None:
            self.set_precision(precision)
        self._device = "tpu" if any(d.platform == "tpu" for d in jax.devices()) else "cpu"
        return self

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    # ------------------------------------------------------------ precision
    def set_precision(self, precision):
        """Select the artifact precision variant to serve
        (PrecisionType or string).  Resolved at Predictor load against the
        manifest's exported variants."""
        from paddle_tpu.static.io import canonicalize_precision

        self._precision = canonicalize_precision(precision)
        return self

    def enable_tensorrt_engine(self, workspace_size=1 << 30, max_batch_size=1,
                               min_subgraph_size=3, precision=None,
                               use_static=False, use_calib_mode=False):
        """TRT-engine analog: XLA is the engine.  Only the precision request
        carries over; the TRT tuning knobs warn."""
        _warn_unsupported(
            "enable_tensorrt_engine", "XLA serves the whole program (no TRT "
            "subgraph engine); workspace/max_batch/min_subgraph/use_static/"
            "use_calib_mode do not apply")
        if precision is not None:
            self.set_precision(precision)
        return self

    # ----------------------------------------------------- optimization etc
    def enable_memory_optim(self, *a, **k):
        _warn_unsupported(
            "enable_memory_optim", "buffer reuse/liveness is performed by "
            "XLA unconditionally; the switch has no additional effect")

    def switch_ir_optim(self, flag=True):
        if not flag:
            _warn_unsupported(
                "switch_ir_optim", "cannot disable XLA optimization of a "
                "compiled artifact; the program stays optimized")

    def switch_ir_debug(self, *a, **k):
        _warn_unsupported(
            "switch_ir_debug", "per-pass IR dumps are not recorded; inspect "
            "the exported <prefix>.pdmodel.txt StableHLO instead")

    def enable_mkldnn(self, *a, **k):
        _warn_unsupported(
            "enable_mkldnn", "CPU serving uses XLA:CPU (no oneDNN tier)")

    def set_cpu_math_library_num_threads(self, n):
        _warn_unsupported(
            "set_cpu_math_library_num_threads", "XLA:CPU threading is set at "
            "process start (XLA_FLAGS=--xla_cpu_multi_thread_eigen / "
            "intra_op_parallelism_threads); runtime changes do not apply")

    def set_optim_cache_dir(self, path):
        """Persist compiled executables (works: the XLA compilation cache)."""
        jax.config.update("jax_compilation_cache_dir", str(path))
        return self

    def disable_glog_info(self):
        """Quiet backend logging (works: jax/absl logger level)."""
        import logging

        logging.getLogger("jax").setLevel(logging.WARNING)
        return self

    def enable_profile(self):
        """Per-run latency accounting on the Predictor (reference
        EnableProfile); read via Predictor.profile_stats()."""
        self._profile = True
        return self

    def enable_warmup(self):
        """Run one zero-input inference at load so first user request pays
        no compile latency (the TRT warmup analog)."""
        self._warmup = True
        return self

    # ------------------------------------------------------------- sharding
    def enable_tensor_parallel(self, mesh, input_specs=None):
        """Serve the loaded program GSPMD-partitioned over `mesh` (reference
        capability: analysis_predictor multi-device serving).  input_specs:
        optional list of PartitionSpec, one per program input (default
        replicated inputs; XLA still partitions the internal compute)."""
        from jax.sharding import Mesh

        self._mesh = mesh.jax_mesh if hasattr(mesh, "jax_mesh") else mesh
        if not isinstance(self._mesh, Mesh):
            raise TypeError(f"mesh must be a jax Mesh/ProcessMesh, got {type(mesh)}")
        self._input_specs = input_specs
        return self


class Predictor:
    def __init__(self, path_prefix_or_config):
        mesh = input_specs = None
        precision = None
        warmup = profile = False
        if isinstance(path_prefix_or_config, Config):
            cfg = path_prefix_or_config
            prefix = cfg.model_path
            mesh, input_specs = cfg._mesh, cfg._input_specs
            precision, warmup, profile = cfg._precision, cfg._warmup, cfg._profile
        else:
            prefix = path_prefix_or_config
        if prefix.endswith(".pdmodel"):
            prefix = prefix[: -len(".pdmodel")]
        self.prefix = prefix
        with open(prefix + ".json") as f:
            self.manifest = json.load(f)
        model_file = self._select_variant(precision)
        with open(model_file, "rb") as f:
            self._exported = jax.export.deserialize(bytearray(f.read()))
        self._input_names = [s["name"] for s in self.manifest["feed"]]
        self._output_names = [s["name"] for s in self.manifest["fetch"]]
        self._inputs = {}
        self._call = self._exported.call
        self._profile = profile
        self._stats = {"count": 0, "total_ms": 0.0, "last_ms": 0.0}
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            specs = input_specs or [PartitionSpec()] * len(self._input_names)
            shardings = [
                s if isinstance(s, NamedSharding)
                else NamedSharding(mesh, s if isinstance(s, PartitionSpec) else PartitionSpec(*s))
                for s in specs
            ]
            # one partitioned executable per mesh: exported.call is traceable,
            # so GSPMD partitions the whole serving program over the mesh
            self._call = jax.jit(self._exported.call, in_shardings=shardings)
        if warmup:
            self.warmup()

    def _select_variant(self, precision):
        """Resolve the requested precision against the exported artifacts."""
        exported_prec = self.manifest.get("precision", "float32")
        variants = self.manifest.get("variants", {})
        if precision is None or precision == exported_prec:
            return self.prefix + ".pdmodel"
        if precision in variants:
            return os.path.join(
                os.path.dirname(self.prefix) or ".", variants[precision])
        if precision in ("bfloat16", "float16") and exported_prec == "float32":
            warnings.warn(
                f"Config precision {precision!r}: artifact was exported at "
                "float32 with no such variant; serving float32 (on TPU, f32 "
                "matmuls already run bf16 MXU passes).  Re-export with "
                f"precision={precision!r} or extra_precisions=[...] for a "
                "cast artifact.",
                RuntimeWarning, stacklevel=3)
            return self.prefix + ".pdmodel"
        raise RuntimeError(
            f"precision {precision!r} requested but the artifact has only "
            f"{[exported_prec] + sorted(variants)} (re-export with "
            "save_inference_model(..., precision=...) or extra_precisions)")

    # reference-style handle API
    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name):
        pred = self

        class _Handle:
            def copy_from_cpu(self, arr):
                pred._inputs[name] = jax.numpy.asarray(arr)

            def reshape(self, shape):
                pass

        return _Handle()

    def get_output_handle(self, name):
        pred = self

        class _Handle:
            def copy_to_cpu(self):
                return np.asarray(pred._last_outputs[pred._output_names.index(name)])

        return _Handle()

    def warmup(self):
        """One inference on zero inputs from the manifest shapes: pays the
        compile/dispatch cost before real traffic."""
        zeros = [
            jax.numpy.zeros(s["shape"], s["dtype"]) for s in self.manifest["feed"]
        ]
        out = self._call(*zeros)
        for o in (out if isinstance(out, (tuple, list)) else [out]):
            jax.block_until_ready(o)
        return self

    def run(self, inputs=None):
        t0 = time.perf_counter() if self._profile else 0.0
        if inputs is not None:
            vals = [jax.numpy.asarray(a) for a in inputs]
        else:
            vals = [self._inputs[n] for n in self._input_names]
        out = self._call(*vals)
        self._last_outputs = list(out) if isinstance(out, (tuple, list)) else [out]
        results = [np.asarray(o) for o in self._last_outputs]
        if self._profile:
            # np.asarray above forced a device->host readback, so the timing
            # covers real execution (axon: block_until_ready lies, readback
            # does not)
            dt = (time.perf_counter() - t0) * 1e3
            self._stats["count"] += 1
            self._stats["total_ms"] += dt
            self._stats["last_ms"] = dt
        return results

    __call__ = run

    def profile_stats(self):
        """{count, total_ms, avg_ms, last_ms} when Config.enable_profile()."""
        s = dict(self._stats)
        s["avg_ms"] = s["total_ms"] / s["count"] if s["count"] else 0.0
        return s

    def clone(self):
        """Cheap handle for another serving thread (reference
        AnalysisPredictor::Clone shares weights): shares the deserialized
        program + compiled executable, separate input/output bindings."""
        twin = object.__new__(Predictor)
        twin.__dict__.update(self.__dict__)
        twin._inputs = {}
        twin._stats = {"count": 0, "total_ms": 0.0, "last_ms": 0.0}
        return twin


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
