"""Cluster worker process entry (`python -m paddle_tpu.serving.cluster_worker`).

Spawned by `serving.cluster.EngineCluster` with a JSON spec in
PADDLE_CLUSTER_SPEC.  Two roles:

- **decode**: owns ONE `GenerationEngine` (prefix cache forced on — it is
  both the page-adoption surface for shipped KV and the substrate of the
  cluster prefix index).  Pops router messages from its inbound ShmRing,
  steps the engine, and pushes per-position token events + completion
  reports.  With a snapshot dir + interval the engine auto-snapshots at
  macro-step boundaries (serving/snapshot.py), and a respawned worker
  RESTORES from the newest valid boundary, re-emitting each resident
  stream from position 0 — the router's per-position merge dedups and
  verifies the overlap, so fail-over is bit-exact.
- **prefill**: builds the model once, computes K/V for a prompt's full
  blocks through the SAME `paged_pour_blocks` math the engine uses, and
  ships the pool-native page bytes (`pool_get_blocks` leaves — int8
  payload + f32 scales for int8 pools, about half the bf16 wire bytes)
  back through the router to the target decode replica, block by block.
- **standby**: the warm-start tier (docs/SERVING_CLUSTER.md).  Builds an
  engine with the cluster's geometry, AOT-warms its macro-step
  executables (`GenerationEngine.warmup` — persistent-cache-served
  compiles), announces `ready` with `warmed=True`, then parks on its
  ring.  A `promote` message hands it a dead replica's snapshot dir: it
  restores the boundary state, carries its warm executables onto the
  restored engine (identical recorded geometry means an identical step
  signature), reports the claimed residents via `resume`, and serves as
  the replica — compile-free on the recovery critical path.

A decode/standby worker spawned with spec["warmup"] warms up BEFORE
pushing its readiness report, so its first heartbeat means "already
compiled" — the router drops the boot-grace carve-out for it
(FailureDetector.mark_warmed) and judges it on the steady-state miss
budget immediately.

Heartbeats ride a background thread bumping a TCPStore counter every
heartbeat_ms/2 — SIGKILL stops the bumps, which is the router's
miss-threshold failure signal.  A worker whose store connection dies
(the router is gone) exits rather than serving into the void.
Crash injection: spec["kill"] = "point:nth" SIGKILLs this process at the
named protocol point (tests/test_serving_cluster_crash.py).
"""

from __future__ import annotations

import json
import os
import pickle
import sys
import threading


def _bootstrap_jax():
    """Same pinning as tests/conftest.py / run_tier1's worker bootstrap:
    CPU platform, exact matmuls, shared persistent compile cache.  The
    cache is configured through _core/compile_cache.configure — NOT raw
    jax.config.update calls — so worker processes get the shared helper's
    exact semantics: gate-zeroing (every small CPU-smoke compile
    persists), the jax.monitoring hit/miss counters the readiness report
    carries, and the FLAGS_compilation_cache_dir listener."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
    from paddle_tpu._core import compile_cache
    from paddle_tpu._core import flags as _flags

    cache = (str(_flags.flag("FLAGS_compilation_cache_dir") or "")
             or os.environ.get("PADDLE_TPU_TEST_CACHE_DIR", "/tmp/jax_cache"))
    compile_cache.configure(cache)


def _load_factory(spec: str):
    """'module:fn' or 'path/to/file.py:fn' -> the model factory."""
    mod, fn = spec.rsplit(":", 1)
    if mod.endswith(".py"):
        import importlib.util

        s = importlib.util.spec_from_file_location("_cluster_model_def", mod)
        m = importlib.util.module_from_spec(s)
        s.loader.exec_module(m)
    else:
        import importlib

        m = importlib.import_module(mod)
    return getattr(m, fn)


def _heartbeat_loop(store, key, period_s):
    while True:
        try:
            store.add(key, 1)
        except OSError:
            os._exit(4)  # the router (store host) is gone: stop serving
        if _HB_STOP.wait(period_s):
            return


_HB_STOP = threading.Event()


class _Out:
    def __init__(self, ring):
        self.ring = ring

    def push(self, msg):
        self.ring.push(pickle.dumps(msg, protocol=4), timeout_ms=30_000)


# ----------------------------------------------------------- cluster adapters
def _cluster_adapter_state(model, rank, seed):
    """Deterministic LoRA weights for one cluster adapter spec: every
    worker derives the SAME state dict from (model geometry, rank, seed)
    — numpy RandomState, host-side, platform-stable — so adapter weights
    never ride the wire and every engine's registration installs
    identical contents (the model-factory construction-identity story
    applied to adapters; router.cluster_adapter_table)."""
    import numpy as np

    from paddle_tpu.nn.lora import LLAMA_TARGETS, _resolve_sublayer

    rng = np.random.RandomState(int(seed))
    layers = model.model.layers
    sd = {}
    for li in range(len(layers)):
        blk = layers[li]
        for t in LLAMA_TARGETS:
            lin = _resolve_sublayer(blk, t)
            a = rng.standard_normal((lin.in_features, int(rank))) * 0.02
            b = rng.standard_normal((int(rank), lin.out_features)) * 0.02
            sd[f"model.layers.{li}.{t}.lora_A"] = a.astype(np.float32)
            sd[f"model.layers.{li}.{t}.lora_B"] = b.astype(np.float32)
    return sd


def _register_cluster_adapters(eng, spec):
    """Register spec["adapters"] IN ORDER on a freshly built engine:
    first-fit slots from 1 + one epoch bump per install lands adapter i
    at (slot i+1, epoch 1) on every worker — the fleet-wide namespace
    cluster_adapter_table promises.  A snapshot-RESTORED engine already
    carries its adapters (the snapshot records registry + slots +
    epochs); re-registering a resident name would bump its epoch out of
    fleet lockstep, so resident names are left untouched."""
    for name, rank, alpha, seed in (spec.get("adapters") or []):
        if name in eng._adapter_registry:
            continue
        eng.register_adapter(
            name, _cluster_adapter_state(eng.model, rank, seed), alpha=alpha)


# --------------------------------------------------------------- decode role
def _warm_report(warm):
    """Readiness-report fields describing this process's warm state: did
    it AOT-warm (and how long that took), and how the persistent compile
    cache served its compiles (process-lifetime jax.monitoring counters —
    zero at exec, so absolute values ARE this boot's counts)."""
    from paddle_tpu._core.compile_cache import compile_stats

    cs = compile_stats()
    return {"warmed": warm is not None,
            "warmup_s": float(warm["seconds"]) if warm else 0.0,
            "cache_hits": int(cs["persistent_cache_hits"]),
            "cache_misses": int(cs["persistent_cache_misses"])}


def _claimed_rids(eng) -> set:
    """The rids a restored engine resurrects: resident slots, the queued
    backlog, and finished-but-undelivered results — the boundary may have
    caught a request between completion and the router's read."""
    tracked = {s.rid for s in eng._slots if s.active}
    tracked.update(eng.pending_requests())
    tracked.update(eng._results)
    return tracked


def _build_decode_engine(spec, model):
    import paddle_tpu as paddle
    from paddle_tpu.serving import GenerationEngine, restore_engine
    from paddle_tpu.serving.snapshot import EngineSnapshot

    snap_dir = spec["snapshot_dir"]
    if snap_dir and spec["snapshot_interval"] > 0:
        paddle.set_flags({
            "FLAGS_engine_snapshot_dir": snap_dir,
            "FLAGS_engine_snapshot_interval": spec["snapshot_interval"]})

    kw = dict(spec["engine"])
    kw["prefix_cache"] = True
    if spec["restore"] and snap_dir and \
            EngineSnapshot(snap_dir).latest_step() is not None:
        eng = restore_engine(model, snap_dir)
        return eng, _claimed_rids(eng)
    return GenerationEngine(model, **kw), set()


def _decode_loop(spec, model, ring_in, out, killer):
    eng, tracked = _build_decode_engine(spec, model)
    _register_cluster_adapters(eng, spec)
    # AOT warm BEFORE the readiness report: the resume push is the claim
    # of this replica's requests, and announcing it with compiles still
    # owed would put trace+compile back on the serving critical path
    warm = eng.warmup() if spec.get("warmup") else None
    out.push({"t": "resume", "rids": sorted(tracked, key=str),
              **_warm_report(warm)})
    _decode_serve(spec, eng, tracked, ring_in, out, killer)


class _DecodeCtx:
    """Mutable decode-serve state threaded through the table-driven
    message handlers (what the pre-PR-19 handle() closure captured)."""

    __slots__ = ("spec", "eng", "tracked", "staging", "sent", "out",
                 "killer", "draining", "snap_dir", "hit_toks_reported")

    def __init__(self, spec, eng, tracked, out, killer):
        self.spec = spec
        self.eng = eng
        self.tracked = tracked
        self.staging: dict = {}
        self.sent: dict = {}
        self.out = out
        self.killer = killer
        self.draining = eng._draining
        self.snap_dir = spec["snapshot_dir"]
        # prefix_hit_tokens watermark already RELAYED to the router in
        # `done` messages (the engine counter is process-global; deltas
        # keep the router's cluster-wide aggregate double-count-free)
        self.hit_toks_reported = 0


# Decode-role message handlers.  One `_decode_msg_<message>` per spec
# message with dst=decode — handler_tables() binds them through
# serving/protocol.py with BOTH directions asserted (a spec message
# without a handler, or a handler the spec no longer names, fails at
# EngineCluster construction, before any fork).
def _decode_msg_submit(ctx, msg):
    if ctx.draining:
        ctx.out.push({"t": "requeue", "rid": msg["rid"]})
        return None
    ctx.eng.add_request(msg["rid"], msg["prompt"],
                        max_new_tokens=msg["max_new"],
                        temperature=msg["temperature"] or None,
                        seed=msg["seed"], nonce=msg["nonce"],
                        adapter=msg.get("adapter"),
                        priority=msg.get("priority", "normal"))
    ctx.killer.hit("decode-after-accept")
    ctx.tracked.add(msg["rid"])
    return None


def _decode_msg_ship_begin(ctx, msg):
    ctx.staging[msg["sid"]] = {"tokens": msg["tokens"],
                               "n": msg["n_blocks"], "k": [], "v": [],
                               "ns": msg.get("ns")}
    return None


def _decode_msg_ship_block(ctx, msg):
    st = ctx.staging.get(msg["sid"])
    if st is not None:
        st["k"].append(msg["k"])
        st["v"].append(msg["v"])
    return None


def _decode_msg_ship_end(ctx, msg):
    import numpy as np

    st = ctx.staging.pop(msg["sid"], None)
    if st is not None and len(st["k"]) == st["n"]:
        n_layers = len(st["k"][0])
        k_blocks = [
            {leaf: np.concatenate(
                [blk[li][leaf] for blk in st["k"]], axis=0)
             for leaf in st["k"][0][li]}
            for li in range(n_layers)]
        v_blocks = [
            {leaf: np.concatenate(
                [blk[li][leaf] for blk in st["v"]], axis=0)
             for leaf in st["v"][0][li]}
            for li in range(n_layers)]
        ctx.eng.adopt_pages(st["tokens"], k_blocks, v_blocks,
                            ns=st.get("ns"))
        ctx.killer.hit("decode-after-adopt")
    # an incomplete ship (a killed prefill worker) just drops:
    # admission falls back to local prefill, nothing is lost
    return None


def _decode_msg_ship_abort(ctx, msg):
    ctx.staging.pop(msg["sid"], None)
    return None


def _decode_msg_drain(ctx, msg):
    ctx.eng.drain(ctx.snap_dir)  # decode specs always carry a snapshot dir
    ctx.draining = True
    ctx.out.push({"t": "drained",
                  "queued": list(ctx.eng.pending_requests())})
    return None


def _decode_msg_stop(ctx, msg):
    return "stop"


def _decode_serve(spec, eng, tracked, ring_in, out, killer):
    handlers, _, _ = handler_tables()
    ctx = _DecodeCtx(spec, eng, tracked, out, killer)

    def emit_progress():
        active = {s.rid for s in eng._slots if s.active}
        queued = set(eng.pending_requests())
        for rid in sorted(tracked, key=str):
            lst = eng.result(rid)
            if lst is None:
                continue
            n0 = ctx.sent.get(rid, 0)
            if len(lst) > n0:
                out.push({"t": "tokens", "rid": rid, "start": n0,
                          "toks": [int(x) for x in lst[n0:]]})
                ctx.sent[rid] = len(lst)
                killer.hit("decode-mid-stream")
            if rid not in active and rid not in queued:
                from paddle_tpu.serving import decode_stats
                hits = int(decode_stats()["prefix_hit_tokens"])
                out.push({"t": "done", "rid": rid,
                          "n": ctx.sent.get(rid, 0),
                          "hit_toks": hits - ctx.hit_toks_reported})
                ctx.hit_toks_reported = hits
                tracked.discard(rid)

    while True:
        busy = eng.has_work()
        try:
            data = ring_in.pop(timeout_ms=1 if busy else 50)
        except TimeoutError:
            data = None
        except BrokenPipeError:
            os._exit(3)
        if data is not None:
            # a message outside the spec raises KeyError -> the fatal
            # path: protocol violations die loudly, never drop silently
            msg = pickle.loads(data)
            if handlers[msg["t"]](ctx, msg) == "stop":
                break
            continue  # drain the inbox before paying for a macro-step
        if busy:
            eng.step()
            emit_progress()
        elif ctx.draining:
            break  # residents finished; queued rids migrated via drained
    out.push({"t": "bye"})


# -------------------------------------------------------------- standby role
class _ParkedCtx:
    """A parked standby's handler context: nothing but the outbound ring
    (its engine is already warm; the handlers only steer the park loop)."""

    __slots__ = ("out",)

    def __init__(self, out):
        self.out = out


def _standby_msg_stop(ctx, msg):
    ctx.out.push({"t": "bye"})
    return "stop"


def _standby_msg_promote(ctx, msg):
    # the park loop breaks out and runs the restore/claim sequence with
    # this message's snapshot_dir/snapshot_interval payload
    return "promote"


def _carries_executables(eng, cfg) -> bool:
    """Whether the standby engine's AOT-compiled macro-steps are valid on
    an engine restored from recorded geometry `cfg` (EngineSnapshot
    .config()): the step signature is geometry-pure — batch, table width,
    pool shapes/dtype — and the compiled executable closes over nothing
    engine-local, so identical geometry means the executables carry.
    Adapter/speculative snapshots never carry (their signatures differ)."""
    return (cfg["max_batch"] == eng.max_batch
            and cfg["block_size"] == eng.block_size
            and cfg["num_blocks"] == eng._num_blocks
            and cfg["kv_cache_dtype"] == eng._kv_dtype
            and not cfg["has_draft"] and cfg["adapters"] is None
            and eng.draft_model is None and eng._pack is None)


def _standby_loop(spec, model, ring_in, out, killer):
    """Warm standby: pay import + trace + (persistent-cache-served)
    compile NOW, against the cluster's engine geometry, then park until a
    `promote` message hands over a dead replica's snapshot dir.  On
    promotion the standby restores the replica's boundary state, carries
    its warm executables onto the restored engine when the recorded
    geometry matches, claims the residents via `resume`, and becomes the
    decode replica — the respawn path's jax import + trace + compile wall
    never lands on the recovery critical path."""
    import paddle_tpu as paddle
    from paddle_tpu.serving import GenerationEngine
    from paddle_tpu.serving.snapshot import EngineSnapshot

    kw = dict(spec["engine"])
    kw["prefix_cache"] = True
    eng = GenerationEngine(model, **kw)
    _register_cluster_adapters(eng, spec)
    killer.hit("standby-mid-warmup")
    warm = eng.warmup() if spec.get("warmup", True) else None
    out.push({"t": "ready", **_warm_report(warm)})

    _, _, handlers = handler_tables()
    ctx = _ParkedCtx(out)
    while True:
        try:
            data = ring_in.pop(timeout_ms=100)
        except TimeoutError:
            continue
        except BrokenPipeError:
            os._exit(3)
        if data is None:
            continue
        msg = pickle.loads(data)
        verdict = handlers[msg["t"]](ctx, msg)
        if verdict == "stop":
            return
        if verdict == "promote":
            break

    snap_dir = msg["snapshot_dir"]
    interval = int(msg.get("snapshot_interval", 0))
    spec = dict(spec)
    spec["snapshot_dir"], spec["snapshot_interval"] = snap_dir, interval
    tracked: set = set()
    if snap_dir and interval > 0:
        # the flags listener clears EVERY engine's compiled steps on ANY
        # set_flags — hold the warm executables across the snapshot-dir
        # arm and reinstall them
        step_fns = dict(eng._step_fns)
        paddle.set_flags({
            "FLAGS_engine_snapshot_dir": snap_dir,
            "FLAGS_engine_snapshot_interval": interval})
        eng._step_fns.update(step_fns)
    store = EngineSnapshot(snap_dir) if snap_dir else None
    if store is not None and store.latest_step() is not None:
        restored = store.restore(model)
        if _carries_executables(eng, store.config()):
            restored._step_fns.update(eng._step_fns)
        eng = restored
        tracked = _claimed_rids(eng)
    out.push({"t": "resume", "rids": sorted(tracked, key=str),
              **_warm_report(warm)})
    _decode_serve(spec, eng, tracked, ring_in, out, killer)


# -------------------------------------------------------------- prefill role
def _prefill_pages(model, prompt, n_blocks, block_size, kv_dtype,
                   scope=None):
    """K/V pages for the prompt's first `n_blocks` FULL blocks, poured
    through the engine's own quantize/pour math into a staging pool and
    extracted as pool-native leaves.  Deterministic: the same prompt
    always ships the same bytes (the bit-exact re-ship contract), int8
    quantization included.  `scope` wraps the forward (an adapter
    request's nn.lora.adapter_prefill_scope: the poured K/V must be the
    ADAPTED model's, exactly what the decode engine's own admission would
    pour for that tenant)."""
    import contextlib

    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models.llama import _model_forward_cached
    from paddle_tpu.ops import paged_attention as pa

    scope = scope if scope is not None else contextlib.nullcontext()
    cfg = model.config
    nkv = cfg.num_key_value_heads
    hd = cfg.hidden_size // cfg.num_attention_heads
    dt = (jnp.int8 if kv_dtype == "int8"
          else jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32)
    span = n_blocks * block_size
    toks = [int(t) for t in prompt[:span]]
    caches = [
        (paddle.zeros([1, 0, nkv, hd], dtype=cfg.dtype),
         paddle.zeros([1, 0, nkv, hd], dtype=cfg.dtype))
        for _ in range(cfg.num_hidden_layers)]
    arr = np.asarray(toks, np.int32).reshape(1, -1)
    with scope, paddle.no_grad():
        _h, caches = _model_forward_cached(
            model.model, paddle.to_tensor(arr), caches, 0)
    idx = jnp.arange(n_blocks, dtype=jnp.int32)

    def pour_and_extract(pool, tensor):
        kv = jnp.moveaxis(tensor._value, 1, 2)  # [1, Nkv, S, H]
        kv = kv.reshape(nkv, n_blocks, block_size, hd).swapaxes(0, 1)
        pool = pa.paged_pour_blocks(pool, kv, idx)
        return {name: np.asarray(a)
                for name, a in pa.pool_get_blocks(pool, idx).items()}

    k_layers, v_layers = [], []
    for k, v in caches:
        kp, vp = pa.alloc_paged_cache(n_blocks, nkv, block_size, hd, dt)
        k_layers.append(pour_and_extract(kp, k))
        v_layers.append(pour_and_extract(vp, v))
    return toks, k_layers, v_layers


class _PrefillCtx:
    """Prefill-role handler context: the shared model plus the resolved
    page geometry every shipment uses.  `pack` holds the cluster's
    deterministic adapters (same construction as every decode engine's
    registration — slot i+1 in spec order) so adapter requests prefill
    through their tenant's weights."""

    __slots__ = ("model", "out", "killer", "block_size", "kv_dtype",
                 "pack")

    def __init__(self, model, out, killer, block_size, kv_dtype,
                 pack=None):
        self.model = model
        self.out = out
        self.killer = killer
        self.block_size = block_size
        self.kv_dtype = kv_dtype
        self.pack = pack


def _build_prefill_pack(model, spec):
    """The prefill worker's AdapterPack: cluster adapter i installed at
    slot i+1 — the same slots cluster_adapter_table names and every
    decode engine's in-order registration lands on.  None without
    cluster adapters."""
    specs = spec.get("adapters") or []
    if not specs:
        return None
    from paddle_tpu.nn.lora import AdapterPack, parse_adapter_state_dict

    pack = AdapterPack(model, rank=int(specs[0][1]),
                       max_adapters=len(specs))
    for i, (name, rank, alpha, seed) in enumerate(specs):
        arrays = parse_adapter_state_dict(
            _cluster_adapter_state(model, rank, seed),
            pack.num_layers, pack.targets, pack.rank)
        pack.set_slot(i + 1, arrays, alpha)
    return pack


def _prefill_msg_stop(ctx, msg):
    return "stop"


def _prefill_msg_prefill(ctx, msg):
    n = int(msg["n_blocks"])
    ns = msg.get("ns")
    scope = None
    if msg.get("adapter") is not None:
        if ctx.pack is None or ns is None:
            raise RuntimeError(
                f"prefill for adapter {msg['adapter']!r} without a "
                "cluster adapter pack/namespace — the router and worker "
                "specs disagree on adapters= (serving/cluster.py)")
        from paddle_tpu.nn.lora import adapter_prefill_scope

        # the wire namespace names the slot whose weights pour this K/V
        scope = adapter_prefill_scope(ctx.model.model.layers, ctx.pack,
                                      int(ns[0]))
    toks, k_layers, v_layers = _prefill_pages(
        ctx.model, msg["prompt"], n, ctx.block_size, ctx.kv_dtype,
        scope=scope)
    ctx.killer.hit("prefill-before-ship")
    sid = msg["sid"]
    ctx.out.push({"t": "page_begin", "sid": sid, "rid": msg["rid"],
                  "tokens": toks, "n_blocks": n,
                  "n_layers": len(k_layers), "ns": ns})
    for bi in range(n):
        ctx.out.push({"t": "page_block", "sid": sid, "i": bi,
                      "k": [{leaf: a[bi:bi + 1] for leaf, a in lay.items()}
                            for lay in k_layers],
                      "v": [{leaf: a[bi:bi + 1] for leaf, a in lay.items()}
                            for lay in v_layers]})
        if bi == n // 2:
            ctx.killer.hit("prefill-mid-ship")
    ctx.out.push({"t": "page_end", "sid": sid})
    ctx.killer.hit("prefill-after-ship")
    ctx.out.push({"t": "shipped", "rid": msg["rid"], "n_blocks": n})
    return None


def _prefill_loop(spec, model, ring_in, out, killer):
    from paddle_tpu._core import flags as _flags

    block_size = int(spec["engine"].get("block_size", 16))
    # resolve EXACTLY like GenerationEngine.__init__: an unset engine
    # kwarg falls back to FLAGS_kv_cache_dtype — a 'bf16' literal here
    # would ship scale-less pages into decode replicas whose env-flagged
    # int8 pools expect payload + scales
    kv_dtype = (spec["engine"].get("kv_cache_dtype")
                or _flags.flag("FLAGS_kv_cache_dtype"))
    _, handlers, _ = handler_tables()
    ctx = _PrefillCtx(model, out, killer, block_size, kv_dtype,
                      pack=_build_prefill_pack(model, spec))
    while True:
        try:
            data = ring_in.pop(timeout_ms=100)
        except TimeoutError:
            continue
        except BrokenPipeError:
            os._exit(3)
        if data is None:
            break
        msg = pickle.loads(data)
        if handlers[msg["t"]](ctx, msg) == "stop":
            break
    out.push({"t": "bye"})


# --------------------------------------------------------------------- main
def main():
    spec = json.loads(os.environ["PADDLE_CLUSTER_SPEC"])
    _bootstrap_jax()

    from paddle_tpu import _native
    from paddle_tpu._core import flags as _flags
    from paddle_tpu.serving.cluster import _KillSpec
    from paddle_tpu.serving.transport import get_transport

    killer = _KillSpec(spec.get("kill") or "")
    # One attach deadline (FLAGS_cluster_attach_timeout_ms) covers every
    # boot-time channel: the store connect, both ring attaches, and — for
    # transport="tcp" — the endpoint-key wait + dial inside attach()
    attach_ms = int(_flags.flag("FLAGS_cluster_attach_timeout_ms"))
    store = _native.TCPStoreClient(port=spec["store_port"],
                                   timeout_ms=attach_ms)
    transport = get_transport(spec.get("transport") or "shm", store=store)
    ring_in = transport.attach(spec["ring_in"], attach_timeout_ms=attach_ms)
    ring_out = transport.attach(spec["ring_out"], attach_timeout_ms=attach_ms)
    hb = threading.Thread(
        target=_heartbeat_loop,
        args=(store, spec["hb_key"], spec["heartbeat_ms"] / 2000.0),
        daemon=True)
    hb.start()

    model = _load_factory(spec["model"])()
    out = _Out(ring_out)
    try:
        if spec["role"] == "decode":
            _decode_loop(spec, model, ring_in, out, killer)
        elif spec["role"] == "standby":
            _standby_loop(spec, model, ring_in, out, killer)
        else:
            _prefill_loop(spec, model, ring_in, out, killer)
    except BrokenPipeError:
        os._exit(3)
    except Exception as e:  # noqa: BLE001 — report, then die loudly
        import traceback

        traceback.print_exc()
        try:
            out.push({"t": "fatal", "err": f"{type(e).__name__}: {e}"})
        except Exception:
            pass
        os._exit(5)
    finally:
        _HB_STOP.set()
    os._exit(0)


_TABLES = None


def handler_tables():
    """(decode, prefill, standby) dispatch tables, bound lazily.

    Lazy so this module's top level stays stdlib-only (the worker entry
    point must not drag numpy/jax in before the role is even known).
    EngineCluster calls this at construction — before any fork — so a
    spec message without a handler, or a stray ``_<role>_msg_*`` handler
    without a spec row, fails loudly in the parent process.
    """
    global _TABLES
    if _TABLES is None:
        from paddle_tpu.serving import protocol

        g = globals()
        _TABLES = (
            protocol.bind_handlers("decode", g, prefix="_decode_msg_",
                                   label="cluster_worker decode loop"),
            protocol.bind_handlers("prefill", g, prefix="_prefill_msg_",
                                   label="cluster_worker prefill loop"),
            protocol.bind_handlers("standby", g, prefix="_standby_msg_",
                                   label="cluster_worker standby park loop"))
    return _TABLES


if __name__ == "__main__":
    main()
