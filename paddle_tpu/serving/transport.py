"""Socket data plane for the serving cluster (ROADMAP item 1,
docs/SERVING_CLUSTER.md "Multi-host data plane").

`TcpRing` is a length-framed byte channel over one TCP connection with
the EXACT producer/consumer contract of `_native.ShmRing`:

- ``push(data, timeout_ms)``   whole-frame-or-nothing enqueue into a
  capacity-bounded send queue.  A full queue past the deadline raises
  ``TimeoutError`` — BACKPRESSURE, never a death verdict.  An oversize
  item raises ``ValueError``.  A ring the peer has gracefully closed
  raises ``BrokenPipeError``.
- ``pop(timeout_ms)``          next whole frame, ``None`` once the peer
  closed and the queue drained, ``TimeoutError`` at the deadline.
  Partial frames persist across pops (torn-frame tolerance): a frame
  split over many TCP segments assembles invisibly.  The capacity bound
  holds END-TO-END: the rx thread stops draining the socket past
  ``capacity`` buffered-unpopped bytes, so TCP flow control backs the
  pipe up until the remote push genuinely blocks — a stalled consumer
  bounds its producer exactly like shm, not just the send window.
- ``close()`` / ``destroy()``  graceful close (a CLOSE sentinel frame
  rides the wire so the peer's pop drains to ``None``) / teardown.

The ONE semantic divergence from shm — and it is deliberate — is death
detection.  ShmRing poisons on a peer dying mid-operation; TCP cannot
distinguish a SIGKILLed peer's FIN/RST from a transient network drop, so
`TcpRing` treats connection loss as SILENCE, not death: the attach side
redials with backoff (``reconnects`` counts the successes), the create
side keeps listening for a replacement connection, unsent whole frames
are retained and re-sent, and a frame in flight across a drop is
delivered at-least-once (the wire protocol is re-emission-safe by
design: nonce identity + the router's per-position merge).  Meanwhile
push sees backpressure and pop sees timeouts — the failure detector
(heartbeats + child exit) remains the only death authority, exactly the
`backpressure-not-death` invariant the protocol model checker proves
over the tcp semantics (static/protocol_lint.py, the `clean-tcp-ring`
scenario with its reconnect-after-drop transition).

Endpoint discovery rides the existing TCPStore control tier: the
creating (router) side publishes ``ep:<ring_name>`` -> ``host:port`` and
the attaching (worker) side blocks on the key under the shared attach
deadline (`FLAGS_cluster_attach_timeout_ms`), then dials on fresh
sockets until the same deadline — a consumer routinely outraces the
producer's bind, the same startup race the ShmRing attach retry absorbs.

`RingTransport` (ShmTransport | TcpTransport) is the construction knob:
`EngineCluster(transport="shm"|"tcp")` / `FLAGS_cluster_transport` pick
one, and cluster.py / cluster_worker.py stay transport-agnostic.
"""

from __future__ import annotations

import collections
import random
import socket
import struct
import threading
import time

__all__ = ["TcpRing", "ShmTransport", "TcpTransport", "get_transport",
           "transport_stats", "reset_transport_stats"]

# ---------------------------------------------------------------- telemetry
# Wire-level counters (cluster_stats() folds them in — the module that
# owns the socket owns the counters): tcp_bytes counts every framed byte
# handed to the kernel, frames_sent/frames_recv count whole data frames
# (the CLOSE sentinel is excluded), reconnects counts connections
# re-established AFTER a drop (first connects are not reconnects).
_TRANSPORT_STATS = {
    "tcp_bytes": 0,
    "reconnects": 0,
    "frames_sent": 0,
    "frames_recv": 0,
}
_stats_mu = threading.Lock()


def transport_stats(reset: bool = False) -> dict:
    """Socket-transport counters (docs/SERVING_CLUSTER.md multi-host
    section).  All-zero when every ring in this process is shm."""
    with _stats_mu:
        out = dict(_TRANSPORT_STATS)
        if reset:
            for k in _TRANSPORT_STATS:
                _TRANSPORT_STATS[k] = 0
    return out


def reset_transport_stats():
    transport_stats(reset=True)


def _bump(key, n=1):
    with _stats_mu:
        _TRANSPORT_STATS[key] += n


# A CLOSE sentinel frame: a length no real frame can carry.  It rides
# the ordinary frame stream so it cannot overtake queued data.
_HDR = struct.Struct(">Q")
_CLOSE_LEN = (1 << 64) - 1
_CLOSE_FRAME = _HDR.pack(_CLOSE_LEN)


class TcpRing:
    """One length-framed byte channel over TCP; ShmRing's contract.

    ``create=True`` binds a listener (ephemeral port unless ``port`` is
    given) and accepts — including REPLACEMENT connections after a drop.
    ``create=False`` dials ``endpoint`` with fresh-socket retries until
    ``attach_timeout_ms`` (dial-before-listen tolerance), then redials in
    the background whenever the connection drops.
    """

    def __init__(self, name: str, capacity: int = 64 << 20, create=True,
                 endpoint=None, attach_timeout_ms: int = 0,
                 host="127.0.0.1", port=0):
        self.name = name
        self.capacity = int(capacity)
        self._create = bool(create)
        self._cv = threading.Condition()
        self._sendq = collections.deque()   # framed bytes, head = in flight
        self._send_bytes = 0
        self._recvq = collections.deque()   # whole payloads, ready to pop
        self._recv_bytes = 0                # payload bytes parked in _recvq
        self._rbuf = bytearray()            # partial frame across segments
        self._conn = None
        self._conn_gen = 0
        self._ever_connected = False
        self._closed_local = False
        self._peer_closed = False
        self._destroyed = False
        self._lsock = None
        if create:
            self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._lsock.setsockopt(socket.SOL_SOCKET,
                                   socket.SO_REUSEADDR, 1)
            self._lsock.bind((host, int(port)))
            self._lsock.listen(4)
            self._lsock.settimeout(0.1)
            self.host, self.port = self._lsock.getsockname()[:2]
        else:
            if endpoint is None:
                raise ValueError("TcpRing attach needs endpoint=(host, "
                                 "port) — publish it via the TCPStore "
                                 "(TcpTransport) or pass it explicitly")
            self.host, self.port = str(endpoint[0]), int(endpoint[1])
            self._set_conn(self._dial_until(attach_timeout_ms))
        self._rx = threading.Thread(target=self._rx_loop, daemon=True,
                                    name=f"tcpring-rx:{name}")
        self._tx = threading.Thread(target=self._tx_loop, daemon=True,
                                    name=f"tcpring-tx:{name}")
        self._rx.start()
        self._tx.start()

    # --------------------------------------------------------- connection
    def _dial_once(self, timeout_s=0.25):
        s = socket.create_connection((self.host, self.port),
                                     timeout=timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(0.2)
        return s

    def _dial_until(self, attach_timeout_ms):
        """Fresh-socket dial retries under ONE deadline — first-refusal
        failure is the wrong contract for a constructor racing the
        listener's bind (the ShmRing attach lesson).  0 keeps the
        fail-on-first-refusal behaviour."""
        deadline = time.monotonic() + max(attach_timeout_ms, 0) / 1000.0
        delay = 0.005
        while True:
            try:
                return self._dial_once()
            except OSError:
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"tcp ring dial failed: {self.name} at "
                        f"{self.host}:{self.port} (no listener within "
                        f"{attach_timeout_ms}ms)") from None
            time.sleep(random.uniform(0, min(delay, 0.1)))
            delay *= 2

    def _set_conn(self, conn):
        with self._cv:
            if self._destroyed:
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._conn = conn
            self._conn_gen += 1
            if self._ever_connected:
                _bump("reconnects")
            self._ever_connected = True
            self._cv.notify_all()

    def _drop(self, gen):
        """Connection loss is SILENCE: discard the torn partial frame
        (the sender re-sends its in-flight frame whole), keep queued
        frames, and let the rx loop accept/redial a replacement."""
        with self._cv:
            if self._conn is None or self._conn_gen != gen:
                return
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None
            del self._rbuf[:]
            self._cv.notify_all()

    # ----------------------------------------------------------- io loops
    def _rx_loop(self):
        while True:
            with self._cv:
                if self._destroyed:
                    return
                conn, gen = self._conn, self._conn_gen
                # Strict >: _rbuf holds at most ONE partial frame (parse
                # runs on every recv), so a max-size frame with an empty
                # recvq reaches exactly `capacity` buffered and must
                # still complete — `>=` would park it forever.
                if (conn is not None and self._recv_bytes
                        + len(self._rbuf) > self.capacity):
                    # Receiver-side backpressure: a consumer that stops
                    # popping must stall the remote producer, or the
                    # capacity contract only bounds the SEND window and
                    # this queue grows without limit.  Stop draining the
                    # socket; TCP flow control fills the sender's kernel
                    # buffer until its push() genuinely blocks.
                    self._cv.wait(0.2)
                    continue
            if conn is None:
                self._reconnect_step()
                continue
            try:
                data = conn.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                self._drop(gen)
                continue
            if not data:  # FIN: silence, not a death verdict
                self._drop(gen)
                continue
            with self._cv:
                if self._conn_gen != gen:
                    continue  # raced a drop: bytes belong to a dead conn
                self._rbuf += data
                self._parse_frames()
                self._cv.notify_all()

    def _reconnect_step(self):
        """One accept (create side) or redial (attach side) attempt."""
        if self._create:
            try:
                conn, _addr = self._lsock.accept()
            except (socket.timeout, OSError):
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(0.2)
            self._set_conn(conn)
            return
        try:
            conn = self._dial_once()
        except OSError:
            time.sleep(random.uniform(0.005, 0.05))
            return
        self._set_conn(conn)

    def _parse_frames(self):
        # caller holds self._cv
        while True:
            if len(self._rbuf) < _HDR.size:
                return
            (n,) = _HDR.unpack_from(self._rbuf)
            if n == _CLOSE_LEN:
                del self._rbuf[:_HDR.size]
                self._peer_closed = True
                continue
            if len(self._rbuf) < _HDR.size + n:
                return  # torn frame: keep the partial for the next recv
            payload = bytes(self._rbuf[_HDR.size:_HDR.size + n])
            del self._rbuf[:_HDR.size + n]
            self._recvq.append(payload)
            self._recv_bytes += len(payload)

    def _tx_loop(self):
        while True:
            with self._cv:
                while (not self._destroyed
                       and (self._conn is None or not self._sendq)):
                    self._cv.wait(0.2)
                if self._destroyed:
                    return
                conn, gen = self._conn, self._conn_gen
                frame = self._sendq[0]
            if not self._send_frame(conn, gen, frame):
                continue
            with self._cv:
                if (self._conn_gen != gen or not self._sendq
                        or self._sendq[0] is not frame):
                    # dropped mid-ack: the frame stays queued and will be
                    # re-sent whole on the replacement connection
                    # (at-least-once across a drop boundary)
                    continue
                self._sendq.popleft()
                self._send_bytes -= len(frame)
                self._cv.notify_all()
            _bump("tcp_bytes", len(frame))
            if frame is not _CLOSE_FRAME:
                _bump("frames_sent")

    _SEND_CHUNK = 1 << 16

    def _send_frame(self, conn, gen, frame):
        """Write one frame in bounded chunks.  The socket's 0.2s timeout
        bounds the TOTAL duration of ``sendall`` (not per-syscall), so a
        frame larger than the kernel send buffer — routine for multi-MB
        ship_block K/V payloads on a real cross-host link — would time
        out mid-send forever if sent whole: timeout -> treated as drop
        -> reconnect -> re-send the SAME frame -> timeout again, a
        livelock loopback tests cannot reproduce.  Chunking makes the
        timeout per-chunk, so any progress resets the clock; a chunk
        timeout means the kernel buffer is full (peer not draining) and
        is BACKPRESSURE — retry on the same connection — while only a
        real socket error is a drop.  Returns True when the frame went
        out whole on this connection."""
        view = memoryview(frame)
        off = 0
        while off < len(view):
            with self._cv:
                if self._destroyed or self._conn_gen != gen:
                    # dropped (or torn down) mid-frame: the peer discards
                    # its torn partial; the frame stays at the sendq head
                    # and is re-sent whole on the replacement connection
                    return False
            try:
                off += conn.send(view[off:off + self._SEND_CHUNK])
            except socket.timeout:
                continue  # kernel buffer full: backpressure, not death
            except OSError:
                self._drop(gen)
                return False
        return True

    # ------------------------------------------------------ ring contract
    def push(self, data: bytes, timeout_ms=-1):
        nb = len(data)
        if nb + _HDR.size > self.capacity:
            raise ValueError("item larger than ring capacity")
        frame = _HDR.pack(nb) + bytes(data)
        deadline = (None if timeout_ms is None or timeout_ms < 0
                    else time.monotonic() + timeout_ms / 1000.0)
        with self._cv:
            while True:
                if self._destroyed or self._closed_local:
                    raise BrokenPipeError("ring closed")
                if self._peer_closed:
                    raise BrokenPipeError("ring closed (peer closed)")
                if self._send_bytes + len(frame) <= self.capacity:
                    self._sendq.append(frame)
                    self._send_bytes += len(frame)
                    self._cv.notify_all()
                    return
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    raise TimeoutError("ring push timed out")
                self._cv.wait(0.2 if rem is None else min(rem, 0.2))

    def pop(self, timeout_ms=-1):
        deadline = (None if timeout_ms is None or timeout_ms < 0
                    else time.monotonic() + timeout_ms / 1000.0)
        with self._cv:
            while True:
                if self._recvq:
                    payload = self._recvq.popleft()
                    self._recv_bytes -= len(payload)
                    self._cv.notify_all()
                    _bump("frames_recv")
                    return payload
                if (self._peer_closed or self._closed_local
                        or self._destroyed):
                    return None  # closed and drained
                rem = (None if deadline is None
                       else deadline - time.monotonic())
                if rem is not None and rem <= 0:
                    raise TimeoutError("ring pop timed out")
                self._cv.wait(0.2 if rem is None else min(rem, 0.2))

    def close(self):
        """Graceful close: queue the CLOSE sentinel BEHIND any pending
        frames so the peer drains everything, then sees None."""
        with self._cv:
            if self._closed_local or self._destroyed:
                return
            self._closed_local = True
            self._sendq.append(_CLOSE_FRAME)
            self._send_bytes += len(_CLOSE_FRAME)
            self._cv.notify_all()

    def destroy(self):
        with self._cv:
            if self._destroyed:
                return
            self._destroyed = True
            conn = self._conn
            self._conn = None
            self._cv.notify_all()
        for s in (conn, self._lsock):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        for t in (self._rx, self._tx):
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout=2.0)

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass


# ================================================================ transports
def _ep_key(ring_name: str) -> str:
    return f"ep:{ring_name}"


class ShmTransport:
    """Today's single-box data plane: `_native.ShmRing`, verbatim."""

    name = "shm"

    def __init__(self, store=None):
        del store  # shm needs no endpoint discovery

    def create(self, ring_name: str, capacity: int):
        from paddle_tpu import _native

        return _native.ShmRing(ring_name, capacity)

    def attach(self, ring_name: str, attach_timeout_ms: int):
        from paddle_tpu import _native

        return _native.ShmRing(ring_name, create=False,
                               attach_timeout_ms=attach_timeout_ms)


class TcpTransport:
    """Multi-host data plane: TcpRing endpoints published through the
    TCPStore control tier (which already spans hosts).  The CREATE side
    (the router) listens and publishes; the ATTACH side (a worker,
    possibly on another host) waits for the endpoint key and dials —
    both halves of the attach share ONE deadline."""

    name = "tcp"

    def __init__(self, store, host="127.0.0.1"):
        if store is None:
            raise ValueError("TcpTransport needs a TCPStore client for "
                             "endpoint discovery")
        self._store = store
        self._host = host

    def create(self, ring_name: str, capacity: int):
        ring = TcpRing(ring_name, capacity, create=True, host=self._host)
        self._store.set(_ep_key(ring_name),
                        f"{ring.host}:{ring.port}".encode())
        return ring

    def attach(self, ring_name: str, attach_timeout_ms: int):
        deadline = time.monotonic() + max(attach_timeout_ms, 1) / 1000.0
        ep = self._store.get(_ep_key(ring_name),
                             timeout_ms=max(attach_timeout_ms, 1))
        host, port = ep.decode().rsplit(":", 1)
        remaining_ms = max(int((deadline - time.monotonic()) * 1000), 1)
        return TcpRing(ring_name, create=False,
                       endpoint=(host, int(port)),
                       attach_timeout_ms=remaining_ms)


def get_transport(kind: str, store=None):
    """Resolve a transport name ("shm" | "tcp"; "" -> the
    FLAGS_cluster_transport default) to a RingTransport instance."""
    if not kind:
        from paddle_tpu._core import flags as _flags

        kind = str(_flags.flag("FLAGS_cluster_transport"))
    if kind == "shm":
        return ShmTransport(store)
    if kind == "tcp":
        return TcpTransport(store)
    raise ValueError(f"unknown cluster transport {kind!r} "
                     "(expected 'shm' or 'tcp')")
