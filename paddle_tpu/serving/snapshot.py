"""Live-engine snapshots: serving-tier fault tolerance (ROADMAP item 5).

Training survives SIGKILL bit-exactly (CheckpointManager); this module
gives the SERVING tier the same property.  `EngineSnapshot` captures a
live `GenerationEngine` — paged K/V pools (bf16 and int8 payload +
scales), block tables and per-block refcounts, the radix prefix-cache
tree (namespaces, epochs, LRU order), the adapter pack with slot/epoch
state, in-flight request state (emitted tokens, per-request PRNG keys,
block lists), the FIFO pending queue, and the submit-time nonce counter —
so a restored engine continues every greedy AND seeded-sampled stream
bit-identically from where the killed engine left off.

The commit rides the SAME atomic protocol as CheckpointManager
(`distributed.checkpoint.manager.commit_dir`: temp dir -> fsynced payload
-> checksummed MANIFEST.json -> one atomic rename), including the
FLAGS_checkpoint_kill_point SIGKILL matrix — crash consistency of engine
snapshots is proven mechanically by the same four kill points
(tests/test_engine_snapshot_crash.py).

Restore builds a FRESH engine from the snapshot's recorded geometry and
pours state back in.  Pool tensors load through the sharded checkpoint
store's shard records (`_assemble_region` — the reshard-on-load path), so
a snapshot taken on a single device restores onto a TP mesh and vice
versa; the mesh lint (FLAGS_verify_sharding) validates placements at
restore-time construction exactly as at normal construction.

`engine.drain()` (snapshot + stop admitting) is the migration /
elastic-scale-down primitive: the returned step restores on another host
or topology with queued requests intact (docs/CHECKPOINT.md).
"""

from __future__ import annotations

import os
import pickle
import shutil
import time

import numpy as np
import jax.numpy as jnp

from paddle_tpu.distributed.checkpoint import (_META_FILE, Metadata,
                                               build_shard_snapshot)
from paddle_tpu.distributed.checkpoint import _assemble_region, _LazyFiles
from paddle_tpu.distributed.checkpoint import manager as _ckpt

__all__ = ["EngineSnapshot", "restore_engine", "snapshot_stats",
           "reset_snapshot_stats", "park_request_state",
           "unpark_request_state"]

_UNSET = object()


# ---------------------------------------------------------------- counters
# Serving-owned (profiler.snapshot_stats() reads them — same contract as
# decode_stats): saves/restores of live engines, committed bytes, wall
# seconds spent capturing+committing, torn snapshots skipped during
# latest_step scans, and drain() calls (the migration primitive).
_SNAPSHOT_STATS = {
    "saves": 0,
    "restores": 0,
    "bytes": 0,
    "snapshot_seconds": 0.0,
    "corrupt_skipped": 0,
    "drains": 0,
}


def snapshot_stats(reset: bool = False) -> dict:
    """Live-engine snapshot counters (docs/CHECKPOINT.md serving section):
    snapshots saved and restored, bytes committed, seconds spent in
    save() (device→host capture + atomic commit), torn/corrupt snapshot
    dirs skipped while resolving latest_step, and engine drains.  Zeros
    when no engine snapshot activity this process."""
    out = dict(_SNAPSHOT_STATS)
    if reset:
        reset_snapshot_stats()
    return out


def reset_snapshot_stats():
    for k in _SNAPSHOT_STATS:
        _SNAPSHOT_STATS[k] = 0.0 if isinstance(_SNAPSHOT_STATS[k], float) else 0


# Torn dirs already counted in corrupt_skipped — PROCESS-wide, because
# engine.snapshot()/restore_engine() construct fresh EngineSnapshot
# instances per call and a kept-for-post-mortem torn dir must not bump
# the health counter again on every later resolve.
_SKIP_COUNTED: set = set()


# ----------------------------------------------------- radix tree state
def _radix_state(tree):
    """Serialize a RadixPrefixCache: DFS node list with parent indices
    (parents always precede their children), preserving each node's key —
    plain chunk tuples and adapter-namespaced ``((slot, epoch), chunk)``
    first-level keys alike — pool block, and LRU clock mark."""
    if tree is None:
        return None
    nodes = []
    stack = [(tree._root, -1)]
    while stack:
        node, pidx = stack.pop()
        if node is tree._root:
            idx = -1
        else:
            nodes.append((pidx, node.chunk, node.block, node.last_used))
            idx = len(nodes) - 1
        for child in node.children.values():
            stack.append((child, idx))
    return {"block_size": tree.block_size, "clock": tree._clock,
            "nodes": nodes}


def _radix_from_state(state):
    from paddle_tpu.serving import RadixPrefixCache, _RadixNode

    tree = RadixPrefixCache(state["block_size"])
    tree._clock = state["clock"]
    built = []
    for pidx, key, block, last_used in state["nodes"]:
        parent = tree._root if pidx < 0 else built[pidx]
        node = _RadixNode(key, block, parent)
        node.last_used = last_used
        parent.children[key] = node
        tree._by_block[block] = node
        built.append(node)
    return tree


# ------------------------------------------------------- host state capture
def _model_record(cfg):
    return {
        "num_hidden_layers": cfg.num_hidden_layers,
        "num_attention_heads": cfg.num_attention_heads,
        "num_key_value_heads": cfg.num_key_value_heads,
        "hidden_size": cfg.hidden_size,
        "vocab_size": cfg.vocab_size,
        "dtype": cfg.dtype,
    }


def _check_model(model, saved, who):
    got = _model_record(model.config)
    if got != saved:
        diff = {k: (saved[k], got[k]) for k in saved if got.get(k) != saved[k]}
        raise ValueError(
            f"{who} does not match the snapshot's geometry — the poured "
            f"K/V was computed by different weights/config: {diff} "
            "(saved, got).  Restore needs the SAME model the snapshot "
            "was taken from; weights themselves ride the training "
            "checkpoint tier, not the engine snapshot.")


def park_request_state(eng, slot):
    """Extract ONE resident request's restorable state — the
    single-request face of the engine snapshot (preemption parking,
    docs/DECODE.md): the slot's host fields plus its pool pages as
    verbatim pool-native bytes (`pool_get_blocks` dicts per layer, the
    same wire face the cluster ships).  The caller releases the slot;
    `unpark_request_state` places the bytes back untouched, so
    park→unpark is bit-exact by construction — never a re-quantization,
    and the (seed, nonce) sampling key plus the len(generated) fold
    index resume the stream token-for-token."""
    from paddle_tpu.ops import paged_attention as pa

    def host(blocks):
        return {name: np.asarray(a) for name, a in blocks.items()}

    pages_k = [host(pa.pool_get_blocks(p, slot.blocks))
               for p in eng._kpools]
    pages_v = [host(pa.pool_get_blocks(p, slot.blocks))
               for p in eng._vpools]
    return {
        "req": slot.req, "seq_len": slot.seq_len, "max_len": slot.max_len,
        "n_blocks": len(slot.blocks), "last_token": slot.last_token,
        "generated": list(slot.generated), "temperature": slot.temperature,
        "key": None if slot.key is None else np.asarray(slot.key),
        "priority": slot.priority,
        "pages_k": pages_k, "pages_v": pages_v,
    }


def unpark_request_state(eng, slot, rec):
    """Re-admit a parked request into `slot`: fresh pool blocks, parked
    pages placed VERBATIM (`pool_set_blocks`), slot state restored.
    Returns False — nothing mutated — when the pool cannot supply the
    blocks right now (the record stays parked for a later boundary)."""
    from paddle_tpu.serving import _PoolExhausted
    from paddle_tpu.ops import paged_attention as pa

    try:
        blocks = eng._alloc(rec["n_blocks"])
    except _PoolExhausted:
        return False
    idx = jnp.asarray(blocks, jnp.int32)
    for li in range(eng._n_layers):
        eng._kpools[li] = pa.pool_set_blocks(eng._kpools[li], idx,
                                             rec["pages_k"][li])
        eng._vpools[li] = pa.pool_set_blocks(eng._vpools[li], idx,
                                             rec["pages_v"][li])
        if eng._pool_sharding is not None:
            eng._kpools[li] = eng._place_pool(eng._kpools[li],
                                              eng._pool_sharding)
            eng._vpools[li] = eng._place_pool(eng._vpools[li],
                                              eng._pool_sharding)
    slot.rid = rec["req"]["rid"]
    slot.active = True
    slot.prefill = None
    slot.seq_len = rec["seq_len"]
    slot.max_len = rec["max_len"]
    slot.blocks = blocks
    slot.last_token = rec["last_token"]
    slot.generated = list(rec["generated"])
    slot.temperature = rec["temperature"]
    slot.key = None if rec["key"] is None else np.asarray(rec["key"])
    slot.d_seq_len = 0
    slot.adapter_slot = 0
    slot.priority = rec.get("priority", 2)
    slot.req = rec["req"]
    return True


def _capture_host_state(eng):
    """Everything but the pool tensors, as picklable host values.  Called
    between macro-steps (the engine is single-threaded host-side), so the
    captured view is a consistent boundary state.

    Overload-discipline state rides as RE-QUEUED submissions: PREFILLING
    slots and parked (preempted) requests both append their original req
    dicts to the captured pending queue — the restored engine replays
    them from (seed, nonce), deterministically — and a prefilling slot's
    reserved blocks are virtually released in the captured allocator
    (mirroring _unref: pages the prefix tree holds stay resident as
    reclaimable cached pages, so mid-prefill poured work survives as
    cache hits)."""
    cfg = {
        "format": 1,
        "max_batch": eng.max_batch,
        "block_size": eng.block_size,
        "num_blocks": eng._num_blocks,
        "eos_token_id": eng.eos_token_id,
        "kv_cache_dtype": eng._kv_dtype,
        "prefill_chunk": eng.prefill_chunk,
        "prefill_chunk_blocks": eng.prefill_chunk_blocks,
        "decode_chunk": eng._decode_chunk,  # ctor value; None = flag-driven
        "prefix_cache": eng._prefix is not None,
        "has_draft": eng.draft_model is not None,
        "num_speculative": eng.num_speculative,
        "model": _model_record(eng.model.config),
        "draft": (_model_record(eng.draft_model.config)
                  if eng.draft_model is not None else None),
        "adapters": (None if eng._pack is None else {
            "rank": eng._pack.rank,
            "alpha": eng._pack.alpha,
            "max_adapters": eng._pack.num_slots - 1,
            "targets": tuple(eng._pack.targets),
        }),
    }
    free = list(eng._free)
    ref = list(eng._ref)
    pending = [dict(req) for req in eng._pending]
    slots = []
    for s in eng._slots:
        if getattr(s, "prefill", None) is not None:
            # PREFILLING: demote to a queued submission and virtually
            # release its reserved blocks in the CAPTURED allocator
            # (mirror _unref — tree-held poured pages stay out of free)
            st = s.prefill
            for b in st.fresh + st.matched:
                ref[b] -= 1
                if ref[b] <= 0 and (eng._prefix is None
                                    or not eng._prefix.holds(b)):
                    free.append(b)
            pending.append(dict(st.req))
            slots.append({
                "rid": None, "active": False, "seq_len": 0, "max_len": 0,
                "blocks": [], "last_token": 0, "generated": [],
                "temperature": 0.0, "key": None, "d_seq_len": 0,
                "adapter_slot": 0, "priority": 1, "req": None,
            })
            continue
        slots.append({
            "rid": s.rid, "active": s.active, "seq_len": s.seq_len,
            "max_len": s.max_len, "blocks": list(s.blocks),
            "last_token": s.last_token, "generated": list(s.generated),
            "temperature": s.temperature,
            "key": None if s.key is None else np.asarray(s.key),
            "d_seq_len": s.d_seq_len, "adapter_slot": s.adapter_slot,
            "priority": getattr(s, "priority", 1),
            "req": getattr(s, "req", None),
        })
    for rec in getattr(eng, "_parked", {}).values():
        pending.append(dict(rec["req"]))
    pack = None
    if eng._pack is not None:
        registry = {}
        for name, (arrays, alpha) in eng._adapter_registry.items():
            registry[name] = ({t: (np.asarray(a), np.asarray(b))
                               for t, (a, b) in arrays.items()}, alpha)
        pack = {
            "registry": registry,
            "slot_names": list(eng._slot_names),
            "slot_epochs": list(eng._slot_epochs),
            "slot_used": list(eng._slot_used),
            "slot_clock": eng._slot_clock,
        }
    return {
        "config": cfg,
        "alloc": {"free": free, "ref": ref},
        "slots": slots,
        "results": {rid: list(v) for rid, v in eng._results.items()},
        "pending": pending,
        "req_counter": eng._req_counter,
        "macro_steps": eng._macro_steps,
        "radix": _radix_state(eng._prefix),
        "pack": pack,
        "spec_stats": (dict(eng._spec_stats)
                       if eng.draft_model is not None else None),
    }


class EngineSnapshot:
    """Step-tagged live-engine snapshot store under `dir` — the serving
    analog of CheckpointManager's policy layer: atomic commits through the
    shared protocol, retention of the newest `max_to_keep` VALID steps,
    corruption skip on resolve, stale-temp sweep.

        store = EngineSnapshot("snaps")
        store.save(engine)                    # step-tagged atomic commit
        eng = store.restore(model)            # newest valid, fresh engine
        eng = store.restore(model, mesh=mesh) # ...onto a different topology
    """

    def __init__(self, dir, max_to_keep=2):
        if max_to_keep is not None and max_to_keep < 1:
            raise ValueError("max_to_keep must be >= 1 (or None for unlimited)")
        self.dir = str(dir)
        self.max_to_keep = max_to_keep
        os.makedirs(self.dir, exist_ok=True)
        self._valid_cache: dict = {}  # step dir -> (manifest mtime, bool)

    # ------------------------------------------------------------- layout
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dir, f"step_{int(step):08d}")

    def all_steps(self) -> list:
        """Committed step numbers, ascending (validity not checked)."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            m = _ckpt._STEP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.dir, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _valid(self, path: str) -> bool:
        """Checksum validity with the manager's (mtime, ok) caching: the
        per-save retention sweep and restore-time re-checks must not
        re-hash every retained snapshot's pool bytes — that sha256 wall
        would land inside the very save_ms the bench gate budgets."""
        mpath = os.path.join(path, _ckpt._MANIFEST)
        try:
            mtime = os.stat(mpath).st_mtime_ns
        except OSError:
            return False
        cached = self._valid_cache.get(path)
        if cached is not None and cached[0] == mtime:
            return cached[1]
        ok = _ckpt.CheckpointManager._verify_manifest(path, mpath)
        self._valid_cache[path] = (mtime, ok)
        return ok

    def latest_step(self):
        """Newest step whose snapshot passes checksum verification, or
        None.  Torn/corrupt directories (a SIGKILL mid-commit, bit rot)
        are skipped and counted in snapshot_stats()['corrupt_skipped'] —
        restore always lands on the newest LOADABLE engine state."""
        for step in reversed(self.all_steps()):
            path = self._step_dir(step)
            if self._valid(path):
                return step
            if path not in _SKIP_COUNTED:  # count each torn dir once
                _SKIP_COUNTED.add(path)
                _SNAPSHOT_STATS["corrupt_skipped"] += 1
        return None

    # --------------------------------------------------------------- save
    def save(self, engine, step=None) -> int:
        """Commit a snapshot of `engine` (default step tag: its macro-step
        count).  Call between step()s — a macro-step boundary; the engine
        never snapshots itself mid-dispatch (maybe_snapshot runs at the
        END of step()).  Returns the committed step number.  The commit
        is the CheckpointManager protocol verbatim, kill points included:
        a crash at any point leaves the previous snapshot restorable."""
        t0 = time.perf_counter()
        from paddle_tpu.ops import paged_attention as pa

        pools = {}
        for li, p in enumerate(engine._kpools):
            pools.update(pa.pool_state_dict(f"pool.k{li}", p))
        for li, p in enumerate(engine._vpools):
            pools.update(pa.pool_state_dict(f"pool.v{li}", p))
        if engine.draft_model is not None:
            for li, p in enumerate(engine._d_kpools):
                pools.update(pa.pool_state_dict(f"pool.dk{li}", p))
            for li, p in enumerate(engine._d_vpools):
                pools.update(pa.pool_state_dict(f"pool.dv{li}", p))
        # device->host sync happens HERE (shard-wise for TP engines: each
        # pool leaf's unique shards + global offsets enter the metadata,
        # which is what lets restore reshard onto any topology)
        arrays, md, fname = build_shard_snapshot(pools)
        extras_blob = pickle.dumps(_capture_host_state(engine), protocol=4)
        step = int(step if step is not None else engine._macro_steps)

        def writer(tmp):
            # the ONE payload-writer body (npz + metadata + extras, each
            # fsynced, kill points included) shared with
            # CheckpointManager._commit
            return _ckpt.write_payload(tmp, arrays, fname, md.to_json(),
                                       extras_blob)

        _final, written = _ckpt.commit_dir(
            self.dir, f"step_{step:08d}", writer,
            manifest_extra={"step": step, "kind": "engine_snapshot"})
        # every byte was hashed moments ago while writing the manifest —
        # seed the verify cache so the retention sweep below (and any
        # restore) need not read it all back
        self._valid_cache[_final] = (
            os.stat(os.path.join(_final, _ckpt._MANIFEST)).st_mtime_ns, True)
        _SNAPSHOT_STATS["saves"] += 1
        _SNAPSHOT_STATS["bytes"] += written
        _SNAPSHOT_STATS["snapshot_seconds"] += time.perf_counter() - t0
        self._gc()
        return step

    # ----------------------------------------------------------------- gc
    def _gc(self):
        """Retention: newest `max_to_keep` VALID steps kept; a torn dir
        newer than every valid snapshot is kept for post-mortem (restore
        skips it anyway); stale temp dirs of dead processes are swept —
        the CheckpointManager rules, on the snapshot store."""
        steps = self.all_steps()
        valid = [s for s in steps if self._valid(self._step_dir(s))]
        keep = set(valid if self.max_to_keep is None
                   else valid[-self.max_to_keep:])
        newest_valid = valid[-1] if valid else None
        for s in steps:
            if s in keep:
                continue
            if s not in valid and (newest_valid is None or s > newest_valid):
                continue
            path = self._step_dir(s)
            shutil.rmtree(path, ignore_errors=True)
            # evict bookkeeping with the dir: a long-lived serving
            # process commits snapshots indefinitely, and undropped
            # entries would grow without bound (a re-torn future dir of
            # the same name must also count afresh)
            self._valid_cache.pop(path, None)
            _SKIP_COUNTED.discard(path)
        _ckpt.sweep_stale_tmp(self.dir)

    def config(self, step=None) -> dict:
        """The recorded engine geometry of snapshot `step` (default:
        newest valid) — the `_capture_host_state` config dict (max_batch,
        block_size, num_blocks, kv_cache_dtype, decode_chunk, model
        record, ...), WITHOUT loading any pool bytes.  This is what lets
        a warm standby decide whether its AOT-compiled executables carry
        onto the restored engine (identical geometry => identical step
        signature) and what a respawned worker warms up against before
        announcing readiness (serving/cluster_worker.py)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise RuntimeError(
                    f"no valid engine snapshot under {self.dir!r}")
        path = self._step_dir(step)
        if not self._valid(path):
            raise RuntimeError(f"engine snapshot {path} is missing or corrupt")
        with open(os.path.join(path, _ckpt._EXTRAS), "rb") as f:
            extras = pickle.load(f)
        return dict(extras["config"])

    # -------------------------------------------------------------- restore
    def restore(self, model, step=None, *, mesh=None, mp_axis="mp",
                draft_model=None, decode_chunk=_UNSET):
        """Rebuild a live engine from snapshot `step` (default: newest
        valid).  `model` (and `draft_model` for speculative snapshots)
        must be the SAME model the snapshot was taken from — geometry is
        validated loudly; weights ride the training checkpoint tier.

        `mesh`/`mp_axis` may DIFFER from the save-time topology: the
        fresh engine is constructed for the target mesh (weights get
        Megatron placements, the mesh lint validates at construction when
        FLAGS_verify_sharding is on) and every pool tensor loads through
        the shard-record assembly path — reshard-on-load, single-device
        ↔ TP in either direction.  `decode_chunk` defaults to the saved
        constructor value; streams are bit-identical for every D, so a
        restore under different FLAGS_decode_chunk stays correct (the
        compiled steps simply rebuild).

        Returns the restored `GenerationEngine`, admitting (a snapshot
        taken by drain() restores OPEN — that is the migration target)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise RuntimeError(
                    f"no valid engine snapshot under {self.dir!r}")
        path = self._step_dir(step)
        if not self._valid(path):
            raise RuntimeError(f"engine snapshot {path} is missing or corrupt")
        with open(os.path.join(path, _ckpt._EXTRAS), "rb") as f:
            extras = pickle.load(f)
        cfg = extras["config"]
        _check_model(model, cfg["model"], "model")
        if cfg["has_draft"] and draft_model is None:
            raise ValueError(
                "snapshot was taken from a speculative engine; pass the "
                "same draft_model=")
        if not cfg["has_draft"] and draft_model is not None:
            raise ValueError(
                "snapshot engine had no draft model; drop draft_model=")
        if cfg["has_draft"]:
            _check_model(draft_model, cfg["draft"], "draft model")

        from paddle_tpu.serving import GenerationEngine
        from collections import deque

        eng = GenerationEngine(
            model,
            max_batch=cfg["max_batch"], block_size=cfg["block_size"],
            num_blocks=cfg["num_blocks"], eos_token_id=cfg["eos_token_id"],
            mesh=mesh, mp_axis=mp_axis, prefill_chunk=cfg["prefill_chunk"],
            draft_model=draft_model,
            num_speculative_tokens=cfg["num_speculative"],
            decode_chunk=(cfg["decode_chunk"] if decode_chunk is _UNSET
                          else decode_chunk),
            prefix_cache=cfg["prefix_cache"],
            kv_cache_dtype=cfg["kv_cache_dtype"],
            adapters=(dict(cfg["adapters"]) if cfg["adapters"] else None),
            # absent in pre-overload snapshots: restore atomic (None ->
            # flag-driven, the constructor default)
            prefill_chunk_blocks=cfg.get("prefill_chunk_blocks"),
        )

        # ---- pools: shard records -> assembled host arrays -> the fresh
        # engine's placement (reshard-on-load; `_place_pool` commits the
        # target sharding so the compiled step's input shardings are the
        # constructed engine's, whatever topology saved the bytes)
        from paddle_tpu.ops import paged_attention as pa

        with open(os.path.join(path, _META_FILE)) as f:
            md = Metadata.from_json(f.read())
        files = _LazyFiles(path)

        def fetch(name, _tmpl):
            tm = md.tensors[name]
            full = tuple(slice(0, d) for d in tm.global_shape)
            # jnp.array COPIES (jnp.asarray zero-copy-aliases the host
            # buffer on CPU): these pools flow into the compiled step's
            # donate_argnums slots, and donating a buffer XLA merely
            # borrows from numpy corrupts the heap — an intermittent
            # SIGSEGV/abort at the next executable teardown, reproduced
            # under loaded tier-1 shards before this copy existed
            return jnp.array(_assemble_region(tm, files, full))

        def load(prefix, template, sharding):
            pool = pa.pool_from_state(template, fetch, prefix)
            return eng._place_pool(pool, sharding)

        eng._kpools = [load(f"pool.k{li}", p, eng._pool_sharding)
                       for li, p in enumerate(eng._kpools)]
        eng._vpools = [load(f"pool.v{li}", p, eng._pool_sharding)
                       for li, p in enumerate(eng._vpools)]
        if draft_model is not None:
            eng._d_kpools = [load(f"pool.dk{li}", p, eng._d_pool_sharding)
                             for li, p in enumerate(eng._d_kpools)]
            eng._d_vpools = [load(f"pool.dv{li}", p, eng._d_pool_sharding)
                             for li, p in enumerate(eng._d_vpools)]

        # ---- allocator + requests
        eng._free = list(extras["alloc"]["free"])
        eng._ref = list(extras["alloc"]["ref"])
        eng._pending = deque(extras["pending"])
        eng._req_counter = extras["req_counter"]
        eng._macro_steps = extras["macro_steps"]
        for sd, slot in zip(extras["slots"], eng._slots):
            slot.rid = sd["rid"]
            slot.active = sd["active"]
            slot.seq_len = sd["seq_len"]
            slot.max_len = sd["max_len"]
            slot.blocks = list(sd["blocks"])
            slot.last_token = sd["last_token"]
            slot.generated = list(sd["generated"])
            slot.temperature = sd["temperature"]
            slot.key = None if sd["key"] is None else np.asarray(sd["key"])
            slot.d_seq_len = sd["d_seq_len"]
            slot.adapter_slot = sd["adapter_slot"]
            slot.priority = sd.get("priority", 1)
            slot.req = sd.get("req")
        # the submit-sequence tie-break resumes past every captured
        # request so post-restore submissions keep FIFO-within-class
        eng._submit_seq = 1 + max(
            [r.get("seq", -1) for r in extras["pending"]]
            + [sd.get("req", {}).get("seq", -1) if sd.get("req") else -1
               for sd in extras["slots"]] + [-1])
        eng._results = {rid: list(v) for rid, v in extras["results"].items()}
        for slot in eng._slots:
            if slot.active:
                # live streams alias their slot's generated list — the
                # same invariant _try_admit establishes
                eng._results[slot.rid] = slot.generated

        # ---- prefix cache (namespaces, epochs, LRU order)
        if cfg["prefix_cache"] and extras["radix"] is not None:
            eng._prefix = _radix_from_state(extras["radix"])

        # ---- adapter pack: registry replayed into slots via the normal
        # scatter (zero-recompile contract intact), epochs restored so a
        # post-restore hot swap strands exactly the right cached subtree
        if extras["pack"] is not None:
            pk = extras["pack"]
            registry = {}
            for name, (arrays, alpha) in pk["registry"].items():
                registry[name] = (
                    {t: (jnp.asarray(a), jnp.asarray(b))
                     for t, (a, b) in arrays.items()}, alpha)
            eng._adapter_registry = registry
            eng._slot_names = list(pk["slot_names"])
            eng._slot_used = list(pk["slot_used"])
            eng._slot_clock = pk["slot_clock"]
            for s, name in enumerate(eng._slot_names):
                if s and name is not None:
                    eng._pack.set_slot(s, *registry[name])
            eng._slot_epochs = list(pk["slot_epochs"])
            refs = [0] * eng._pack.num_slots
            for slot in eng._slots:
                if slot.active:
                    refs[slot.adapter_slot] += 1
            eng._slot_refs = refs
            import paddle_tpu.serving as _serving

            _serving._LORA_STATS["slots_total"] = eng._pack.num_slots - 1
            _serving._LORA_STATS["slots_resident"] = eng._resident_count()

        if eng.draft_model is not None and extras["spec_stats"] is not None:
            eng._spec_stats = dict(extras["spec_stats"])
        _SNAPSHOT_STATS["restores"] += 1
        return eng


def restore_engine(model, dir, step=None, *, mesh=None, mp_axis="mp",
                   draft_model=None, decode_chunk=_UNSET):
    """Restore a live engine from the newest valid snapshot under `dir`
    (or an explicit `step`) — `EngineSnapshot(dir).restore(...)`; see
    that method for the topology-migration and bit-exact-resume
    contract."""
    return EngineSnapshot(dir).restore(
        model, step=step, mesh=mesh, mp_axis=mp_axis,
        draft_model=draft_model, decode_chunk=decode_chunk)
