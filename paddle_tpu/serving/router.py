"""Router tier for the disaggregated serving cluster (docs/SERVING_CLUSTER.md).

This module is the TRANSPORT-AGNOSTIC half of serving/cluster.py: every
routing/robustness decision lives here as plain host-side state machines so
the contracts are unit-testable without spawning a single process.
cluster.py wires them to real OS processes over the native TCPStore and
ShmRing.

Pieces (reference lineage: the fleet/elastic failure-detection + relaunch
design, docs/DISTRIBUTED.md failure-modes table, applied to serving):

- `block_hashes` / `ClusterPrefixIndex` — the cluster-level prefix cache
  index: chained hashes over FULL prompt blocks (the same page granularity
  as the engine's radix tree, docs/DECODE.md) map to the replica whose
  radix tree already holds those pages, so shared-system-prompt requests
  route to the replica that can skip their prefill.
- `IntakeLog` — the router's durable accepted-request log: an accepted
  request is fsynced BEFORE it is dispatched, so a router crash (or a
  replica crash) can never lose it; token deliveries and completions are
  logged too, so a restarted router replays finished streams instead of
  re-serving them.
- `FailureDetector` — per-replica heartbeat miss counting: a replica whose
  heartbeat counter stops advancing for `miss_threshold` consecutive
  heartbeat periods is declared dead (SIGKILL leaves no goodbye).
- `RequestRouter` — request identity (router-assigned idempotent ids +
  submit-time nonces), replica selection (prefix affinity, then least
  outstanding), per-position token dedup/merge (a re-dispatched or
  snapshot-restored stream re-emits a prefix; the router keeps ONE
  canonical stream and verifies the overlap bit-for-bit), and the
  re-dispatch set on replica death/drain.
- `retry_backoff` — timeouts + capped exponential backoff with jitter for
  every store/ring operation.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import time

__all__ = ["block_hashes", "cluster_adapter_table", "ClusterPrefixIndex",
           "IntakeLog", "FailureDetector", "RequestRouter", "retry_backoff"]


# ------------------------------------------------------------- retry helper
def retry_backoff(fn, *, timeout_s=5.0, base_s=0.005, cap_s=0.25,
                  retry_on=(TimeoutError, ConnectionError), rng=None,
                  on_retry=None):
    """Run `fn()` until it returns, retrying `retry_on` failures with
    capped exponential backoff + full jitter under ONE deadline.

    The deadline is shared across attempts (the TCPStore `wait` lesson:
    per-attempt budgets multiply into unbounded stalls).  When the
    deadline passes, the LAST failure re-raises — never a swallowed
    timeout.  `on_retry(exc)` is called before each sleep (the cluster
    counts ship_retries through it); `rng` (random.Random) makes jitter
    deterministic under test."""
    rng = rng or random
    deadline = time.monotonic() + timeout_s
    delay = base_s
    while True:
        try:
            return fn()
        except retry_on as e:
            if time.monotonic() >= deadline:
                raise
            if on_retry is not None:
                on_retry(e)
            time.sleep(rng.uniform(0, min(delay, cap_s)))
            delay *= 2


# ---------------------------------------------------------- prefix affinity
def block_hashes(tokens, block_size, ns=None):
    """Chained hashes of the prompt's FULL blocks — the cluster-wide key
    for one engine page (docs/DECODE.md page granularity).  Hash i covers
    tokens[0 : (i+1)*block_size] via chaining, so equal hash means equal
    whole prefix, not merely an equal chunk — exactly the radix-tree path
    identity, without shipping token lists around the cluster.  `ns` is
    the (slot, epoch) adapter namespace: it seeds the chain, so the same
    prompt under different adapters (different K/V!) hashes to disjoint
    chains — the cluster-index mirror of the engine radix tree's
    namespaced walk."""
    out = []
    h = hashlib.sha256()
    if ns is not None:
        h.update(f"ns:{int(ns[0])},{int(ns[1])};".encode())
    bs = int(block_size)
    for bi in range(len(tokens) // bs):
        chunk = tokens[bi * bs:(bi + 1) * bs]
        h.update((",".join(str(int(t)) for t in chunk) + ";").encode())
        out.append(h.hexdigest()[:24])
    return out


def cluster_adapter_table(adapter_specs):
    """{name: (slot, epoch)} the cluster's deterministic adapter
    namespace: ``adapter_specs`` is EngineCluster's
    ``[(name, rank, alpha, seed), ...]`` list, and every worker registers
    exactly these, in order, on a freshly built engine at boot —
    first-fit slots from 1 and one epoch bump per install
    (GenerationEngine.register_adapter / _try_install), so adapter i
    lands at (slot i+1, epoch 1) across the whole fleet.  Weights and
    epochs never ride the wire; construction identity IS the namespace
    agreement (the same story as the model factory), and a lockstep unit
    test pins this table to the engine's actual registration behaviour."""
    return {str(s[0]): (i + 1, 1) for i, s in enumerate(adapter_specs)}


class ClusterPrefixIndex:
    """host-side map: block hash -> replicas believed to hold that page.

    The router records optimistically at ROUTE time (the replica it picks
    will insert those pages into its radix tree when prefill commits) and
    drops a replica's entries wholesale on death/drain — a dead replica's
    pages are gone, and stale affinity would keep routing hot prompts at a
    corpse.  `best_replica` returns the replica covering the LONGEST
    prefix of the prompt's hash chain, with the depth, so the caller can
    weigh affinity against load."""

    def __init__(self, block_size):
        self.block_size = int(block_size)
        self._by_hash: dict[str, set] = {}
        self._ranks: dict[int, set] = {}  # rank -> its hashes (for drops)

    def record(self, rank, tokens, ns=None):
        for hx in block_hashes(tokens, self.block_size, ns=ns):
            self._by_hash.setdefault(hx, set()).add(rank)
            self._ranks.setdefault(rank, set()).add(hx)

    def drop_rank(self, rank):
        for hx in self._ranks.pop(rank, ()):  # noqa: B905
            holders = self._by_hash.get(hx)
            if holders is not None:
                holders.discard(rank)
                if not holders:
                    del self._by_hash[hx]

    def best_replica(self, tokens, among=None, ns=None):
        """(rank, depth) of the replica holding the longest cached hash
        chain of `tokens` under adapter namespace `ns` (depth = matched
        full blocks), or (None, 0).  `among` restricts candidates (the
        live replica set)."""
        depth_by_rank: dict[int, int] = {}
        for i, hx in enumerate(block_hashes(tokens, self.block_size, ns=ns)):
            holders = self._by_hash.get(hx)
            if not holders:
                break
            for r in holders:
                if among is None or r in among:
                    # chained hashes: holding hash i implies the whole
                    # prefix, so depth is simply the deepest level seen
                    depth_by_rank[r] = i + 1
        if not depth_by_rank:
            return None, 0
        best = max(depth_by_rank.items(), key=lambda kv: (kv[1], -kv[0]))
        return best[0], best[1]


# ------------------------------------------------------------- durable log
class IntakeLog:
    """Append-only fsynced JSONL journal of accepted requests and their
    deliveries — the router's source of truth across its OWN death.

    Records: {"ev": "submit", rid, prompt, opts, nonce}
             {"ev": "tokens", rid, start, toks}
             {"ev": "done", rid, n}
    A SUBMIT is fsynced before the router acknowledges or dispatches it
    (an accepted request must survive anything); token/done records ride
    the same fsync discipline so a restarted router re-serves COMPLETED
    streams from the log instead of re-running them.  Replay tolerates a
    torn final line (a kill mid-append) by discarding it — the same
    "prior state always recoverable" stance as the snapshot commit."""

    def __init__(self, path):
        self.path = str(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")

    def append(self, record: dict):
        self._f.write(json.dumps(record, separators=(",", ":")) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self):
        try:
            self._f.close()
        except OSError:
            pass

    @staticmethod
    def replay(path):
        """All intact records, in order; a torn trailing line (kill
        mid-append) is dropped, a torn INTERIOR line fails loudly —
        that is corruption, not a crash artifact."""
        out = []
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().split("\n")
        except FileNotFoundError:
            return out
        for i, line in enumerate(lines):
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                if i == len(lines) - 1 or all(
                        not later for later in lines[i + 1:]):
                    break  # torn tail: the append the kill interrupted
                raise ValueError(
                    f"intake log {path!r} corrupt at line {i + 1} "
                    "(non-trailing unparseable record)")
        return out


# -------------------------------------------------------- failure detection
class FailureDetector:
    """Miss-threshold heartbeat detector over monotonically increasing
    per-replica counters (replicas bump a TCPStore key; SIGKILL stops the
    bumps).  One `observe(rank, counter)` per router poll; `dead_ranks()`
    names replicas whose counter has not advanced for `miss_threshold`
    heartbeat periods.  A `clock` injection point keeps the unit tests
    off the wall clock."""

    def __init__(self, heartbeat_ms, miss_threshold, clock=time.monotonic,
                 on_miss=None, boot_grace_s=None):
        """boot_grace_s: how long a tracked rank may go WITHOUT ITS FIRST
        heartbeat before it counts as dead (default: the larger of the
        miss budget and 30s).  A fresh worker pays interpreter + jax
        import + first compiles before its heartbeat thread's first bump
        reaches the store; judging that boot window by the steady-state
        miss budget declares healthy replicas dead at spawn and melts the
        cluster into a respawn loop (observed, not hypothetical)."""
        self.heartbeat_s = heartbeat_ms / 1000.0
        self.miss_threshold = int(miss_threshold)
        self.boot_grace_s = (boot_grace_s if boot_grace_s is not None
                             else max(self.heartbeat_s
                                      * self.miss_threshold, 30.0))
        self._clock = clock
        self._on_miss = on_miss  # callback(n_new_misses) -> telemetry
        # rank -> [counter, t_advance, misses_reported, ever_beat]
        self._state: dict = {}

    def track(self, rank):
        self._state.setdefault(rank, [-1, self._clock(), 0, False])

    def forget(self, rank):
        self._state.pop(rank, None)

    def observe(self, rank, counter):
        st = self._state.setdefault(rank, [-1, self._clock(), 0, False])
        if counter > st[0]:
            booted = st[0] >= 0  # the -1 -> 0 step is key creation, not a beat
            st[0], st[1], st[2] = counter, self._clock(), 0
            st[3] = st[3] or booted

    def mark_warmed(self, rank):
        """Arm steady-state miss accounting for `rank` NOW: a worker that
        announced `warmed=True` in its ready/resume record has already
        paid import + trace + compile, so nothing slow stands between it
        and its next heartbeat — the boot-grace carve-out (which exists
        only because cold boots stall for seconds before the first bump)
        does not apply.  A warm worker that then stalls is declared dead
        within the NORMAL miss threshold.  Cold boots (no warmed record)
        keep the grace window."""
        st = self._state.setdefault(rank, [-1, self._clock(), 0, False])
        st[1] = self._clock()  # the miss window starts at the report
        st[3] = True

    def misses(self, rank):
        st = self._state.get(rank)
        if st is None or not st[3]:
            return 0
        return int((self._clock() - st[1]) / self.heartbeat_s)

    def dead_ranks(self):
        """Ranks past the miss threshold (or past the boot grace without
        a first heartbeat).  New misses since the last call are reported
        through `on_miss` exactly once each, so the heartbeats_missed
        counter is a true count, not a poll rate."""
        dead = []
        for rank, st in self._state.items():
            if not st[3]:
                if self._clock() - st[1] >= self.boot_grace_s:
                    dead.append(rank)
                continue  # boot window: no miss accounting yet
            missed = int((self._clock() - st[1]) / self.heartbeat_s)
            if missed > st[2] and self._on_miss is not None:
                self._on_miss(missed - st[2])
            st[2] = max(st[2], missed)
            if missed >= self.miss_threshold:
                dead.append(rank)
        return dead


# ------------------------------------------------------------- the router
class _Req:
    __slots__ = ("rid", "prompt", "opts", "nonce", "owner", "tokens",
                 "done", "shipped")

    def __init__(self, rid, prompt, opts, nonce):
        self.rid = rid
        self.prompt = list(prompt)
        self.opts = dict(opts)
        self.nonce = int(nonce)
        self.owner = None       # replica rank currently serving it
        self.tokens: list = []  # canonical delivered stream
        self.done = False
        self.shipped = False    # routed through a prefill worker


class RequestRouter:
    """The router's decision core: request identity, replica selection,
    canonical stream assembly with per-position dedup, and the
    re-dispatch set on replica death or drain.  Transport-free (cluster.py
    owns rings/processes); durable through `IntakeLog`.

    Idempotent ids: a rid resubmitted while known (an at-least-once
    client, or an intake-log replay) is NOT a new request — it keeps its
    original nonce, so its sampled stream is pinned at first acceptance.

    Bit-exact fail-over rests on two facts this class enforces: (a) the
    (seed, nonce) pair is request identity — assigned here once, carried
    to whichever replica serves the request, so a re-dispatched stream is
    THE stream; (b) re-emitted prefixes (intake-log replay from scratch,
    or a snapshot-restored replica re-walking from its boundary) merge by
    position and must MATCH the canonical tokens — divergence raises
    instead of silently corrupting a client stream."""

    def __init__(self, block_size, log_path=None, adapter_ns=None):
        """adapter_ns: {adapter name: (slot, epoch)} — the cluster's
        deterministic adapter namespace table (cluster_adapter_table);
        requests carrying an ``adapter`` opt route and index under it."""
        self.index = ClusterPrefixIndex(block_size)
        self.log = IntakeLog(log_path) if log_path else None
        self.adapter_ns = dict(adapter_ns or {})
        self._reqs: dict = {}
        self._nonce = 0
        self._outstanding: dict[int, set] = {}  # rank -> open rids

    # ------------------------------------------------------------ lifecycle
    def add_replica(self, rank):
        self._outstanding.setdefault(rank, set())

    def replicas(self):
        return sorted(self._outstanding)

    def load(self, rank):
        return len(self._outstanding.get(rank, ()))

    # ------------------------------------------------------------- intake
    def submit(self, rid, prompt, **opts):
        """Accept a request: assign its nonce (idempotently — a known rid
        keeps its first), journal it durably, and return the _Req.  The
        caller dispatches; acceptance is already crash-proof."""
        req = self._reqs.get(rid)
        if req is not None:
            return req
        req = _Req(rid, prompt, opts, self._nonce)
        self._nonce += 1
        self._reqs[rid] = req
        if self.log is not None:
            self.log.append({"ev": "submit", "rid": rid,
                             "prompt": [int(t) for t in prompt],
                             "opts": opts, "nonce": req.nonce})
        return req

    def restore(self, records):
        """Rebuild router state from `IntakeLog.replay` records: completed
        streams are final (never re-dispatched), partial streams keep
        their delivered prefix as the dedup base, and the nonce counter
        resumes PAST every logged nonce so post-restart submissions can
        never collide with pre-crash identities."""
        for rec in records:
            if rec["ev"] == "submit":
                req = _Req(rec["rid"], rec["prompt"], rec.get("opts", {}),
                           rec["nonce"])
                self._reqs[rec["rid"]] = req
                self._nonce = max(self._nonce, req.nonce + 1)
            elif rec["ev"] == "tokens":
                req = self._reqs.get(rec["rid"])
                if req is not None:
                    self._merge(req, rec["start"], rec["toks"], log=False)
            elif rec["ev"] == "done":
                req = self._reqs.get(rec["rid"])
                if req is not None:
                    req.done = True

    # ------------------------------------------------------------- routing
    def ns_of(self, req):
        """The (slot, epoch) adapter namespace a request's pages live
        under, or None for base-model requests (and unknown names — the
        cluster validates names at submit, before anything is journaled)."""
        adapter = req.opts.get("adapter")
        return self.adapter_ns.get(adapter) if adapter is not None else None

    def pick_replica(self, prompt, among=None, ns=None):
        """Prefix affinity first (the replica already holding the longest
        cached page chain of this prompt, within adapter namespace `ns`),
        least-outstanding as the tie-break and the cold-prompt default."""
        live = sorted(among if among is not None else self._outstanding)
        if not live:
            raise RuntimeError("no live replicas to route to")
        rank, depth = self.index.best_replica(prompt, among=set(live), ns=ns)
        if rank is not None and depth > 0:
            return rank
        return min(live, key=lambda r: (self.load(r), r))

    def assign(self, rid, rank, shipped=False):
        req = self._reqs[rid]
        req.owner = rank
        req.shipped = shipped
        self._outstanding.setdefault(rank, set()).add(rid)
        self.index.record(rank, req.prompt, ns=self.ns_of(req))

    def unassign(self, rid):
        """Release a request whose dispatch could not be DELIVERED (ring
        backpressure): owner cleared, it returns to the unassigned
        backlog for a later dispatch.  Distinct from replica death — the
        replica is fine, the message never reached its ring."""
        req = self._reqs.get(rid)
        if req is None or req.owner is None:
            return
        self._outstanding.get(req.owner, set()).discard(rid)
        req.owner = None

    # ------------------------------------------------------------- delivery
    def _merge(self, req, start, toks, log=True):
        """Merge a token run at absolute position `start`; the overlap
        with already-delivered tokens must match bit-for-bit (re-emission
        after fail-over is expected, divergence is corruption).  Returns
        the NEWLY appended tokens."""
        toks = [int(t) for t in toks]
        have = len(req.tokens)
        if start > have:
            raise RuntimeError(
                f"request {req.rid!r}: token run starts at {start} but "
                f"only {have} delivered — a gap means a lost event, "
                "which the ring transport cannot produce")
        overlap = req.tokens[start:start + len(toks)]
        if overlap != toks[:len(overlap)]:
            raise RuntimeError(
                f"request {req.rid!r}: re-emitted tokens diverge from the "
                f"delivered stream at position {start} "
                f"({overlap[:8]} vs {toks[:8]}) — fail-over must be "
                "bit-exact (docs/SERVING_CLUSTER.md)")
        new = toks[len(overlap):]
        if new:
            req.tokens.extend(new)
            if log and self.log is not None:
                self.log.append({"ev": "tokens", "rid": req.rid,
                                 "start": have, "toks": new})
        return new

    def on_tokens(self, rid, start, toks):
        req = self._reqs.get(rid)
        if req is None or req.done:
            return []  # late echo from a lame duck after completion
        return self._merge(req, start, toks)

    def on_done(self, rid, total):
        """Mark `rid` complete.  Returns True only on the FIRST
        completion — the wire is at-least-once (TcpRing re-sends its
        in-flight frame whole after a drop), so callers folding `done`
        payload deltas into counters must gate on this."""
        req = self._reqs.get(rid)
        if req is None:
            return False
        if len(req.tokens) != total:
            raise RuntimeError(
                f"request {rid!r}: replica reports {total} tokens done, "
                f"router delivered {len(req.tokens)}")
        first_done = not req.done
        req.done = True
        if req.owner is not None:
            self._outstanding.get(req.owner, set()).discard(rid)
        if first_done and self.log is not None:
            self.log.append({"ev": "done", "rid": rid, "n": total})
        return first_done

    # ------------------------------------------------------------ fail-over
    def on_replica_dead(self, rank):
        """A replica failed (heartbeat threshold or process death): drop
        its prefix-index entries and return the accepted-but-unfinished
        rids it owned — the re-dispatch set.  Completed requests are
        final in the log and never move."""
        self.index.drop_rank(rank)
        orphans = sorted(self._outstanding.pop(rank, set()))
        out = []
        for rid in orphans:
            req = self._reqs[rid]
            if not req.done:
                req.owner = None
                out.append(rid)
        return out

    def on_drained(self, rank, queued_rids):
        """Graceful scale-down: the drained replica keeps serving its
        RESIDENTS to completion (their events still merge), but its
        queued (never-started) requests come home for re-dispatch, and
        its pages leave the prefix index (the process is exiting)."""
        self.index.drop_rank(rank)
        out = []
        for rid in queued_rids:
            req = self._reqs.get(rid)
            if req is not None and not req.done:
                self._outstanding.get(rank, set()).discard(rid)
                req.owner = None
                out.append(rid)
        return out

    # -------------------------------------------------------------- queries
    def request(self, rid):
        return self._reqs.get(rid)

    def result(self, rid):
        req = self._reqs.get(rid)
        if req is None or not req.done:
            return None
        return list(req.tokens)

    def unfinished(self):
        return sorted(r.rid for r in self._reqs.values() if not r.done)

    def unassigned(self):
        return sorted(r.rid for r in self._reqs.values()
                      if not r.done and r.owner is None)
