"""Continuous-batching generation engine over the paged-KV tier.

Reference lineage: the block-attention serving stack —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and the
FastDeploy/PaddleNLP continuous-batching servers built on it (requests
share one block pool through per-request block tables, joining and leaving
the decode batch between steps).

TPU-native design: the decode batch has a FIXED number of slots, so every
step — any mix of live requests — reuses ONE compiled XLA program (static
shapes are the whole game on TPU; the reference's GPU kernel re-launches
per ragged batch instead).  A host-side block allocator hands pool pages
to requests and recycles them at completion; inactive slots park on a
dedicated scratch page each so the shared pool is never corrupted by
masked lanes.  Prefill runs per admitted request and pours its K/V into
pool pages; decode then advances all live slots together.
"""

from __future__ import annotations

import time
import weakref
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core import flags as _flags

__all__ = ["GenerationEngine", "decode_stats", "reset_decode_stats"]


# --------------------------------------------------------- decode telemetry
# Process-wide decode counters (profiler.decode_stats() reads them): one
# dispatch = one compiled-program launch; sync_seconds = host time blocked
# materializing device results (the per-token round-trip macro-stepping
# amortizes); tokens counts EMITTED tokens (masked tail lanes excluded).
_DECODE_STATS = {
    "dispatches": 0,
    "tokens": 0,
    "sync_seconds": 0.0,
    "step_seconds": 0.0,
    "macro_steps": 0,
    "last_chunk": 0,
}


def decode_stats(reset: bool = False) -> dict:
    """Serving decode counters: dispatches, emitted tokens, host sync
    seconds, total step() seconds, and derived tokens_per_sec.  A healthy
    macro-stepping engine shows tokens >> dispatches; tokens ~= dispatches
    means the per-token path (FLAGS_decode_chunk=1) is active."""
    out = dict(_DECODE_STATS)
    out["tokens_per_sec"] = (
        out["tokens"] / out["step_seconds"] if out["step_seconds"] else 0.0)
    if reset:
        reset_decode_stats()
    return out


def reset_decode_stats():
    for k in _DECODE_STATS:
        _DECODE_STATS[k] = 0.0 if isinstance(_DECODE_STATS[k], float) else 0


# Live engines hold compiled decode executables; any flag change may alter
# what those programs traced (FLAGS_decode_chunk, matmul precision, ...), so
# set_flags drops them — the same contract as the eager dispatch cache.
_ENGINES: "weakref.WeakSet[GenerationEngine]" = weakref.WeakSet()


@_flags.on_change
def _invalidate_decode_steps(_changed):
    for eng in list(_ENGINES):
        eng._step_fns.clear()
        eng._draft_fn = eng._verify_fn = None


@dataclass
class _Slot:
    rid: object = None
    active: bool = False
    seq_len: int = 0          # tokens stored in the pool (incl. prompt)
    max_len: int = 0          # seq_len limit for this request
    blocks: list = field(default_factory=list)
    last_token: int = 0
    generated: list = field(default_factory=list)
    # per-request decode config (temperature-sampling tier; 0 = greedy)
    temperature: float = 0.0
    key: object = None        # precomputed PRNG key (seed + request nonce)
    d_seq_len: int = 0        # draft-pool coverage (speculative tier)


class GenerationEngine:
    """Greedy continuous-batching decode over a shared paged-KV pool.

    Usage:
        eng = GenerationEngine(model, max_batch=4, block_size=16, num_blocks=64)
        eng.add_request("a", prompt_ids_a, max_new_tokens=8)
        while eng.has_work():
            for rid, toks in eng.step().items(): ...
        eng.result("a")  # -> list of generated token ids

    step() advances one MACRO-STEP of D = decode_chunk tokens per
    dispatch (D resolves to FLAGS_decode_chunk, default 8, when the
    constructor arg is None) and returns {rid: [tokens...]}; only at
    D == 1 — an explicit decode_chunk=1 or the flag set to 1 — does it
    return the legacy per-token {rid: token} shape.  Consumers that
    stream token-by-token should pass decode_chunk=1 or iterate the
    lists; `result(rid)` is unaffected either way (docs/DECODE.md).
    """

    def __init__(self, model, max_batch=4, block_size=16, num_blocks=128,
                 eos_token_id=None, mesh=None, mp_axis="mp",
                 prefill_chunk=None, draft_model=None,
                 num_speculative_tokens=4, decode_chunk=None):
        """mesh: optional ProcessMesh/jax Mesh with an `mp_axis` dimension —
        the engine then serves TENSOR-PARALLEL: weights get Megatron
        placements (models.llama.shard_llama), the paged-KV pool is sharded
        over the KV-head dim, and the ONE compiled decode program runs
        GSPMD-partitioned over the mesh (VERDICT r3 #6; reference capability:
        analysis_predictor multi-device serving).

        decode_chunk (None -> FLAGS_decode_chunk): macro-step width D —
        step() advances D tokens per compiled dispatch (a lax.scan over the
        single-token step with donated pools), admitting/retiring requests
        only at macro-step boundaries; rows that finish mid-chunk are
        masked onto their scratch page for the rest of the chunk (their
        K/V writes never touch the shared pool) and their surplus tokens
        are dropped on the host.  Token streams are bit-identical for
        every D.  step() returns {rid: token} when D == 1 (back-compat)
        and {rid: [tokens...]} when D > 1.  Ignored by speculative engines
        (their tick is already multi-token)."""
        cfg = model.config
        self.model = model
        if prefill_chunk is not None and int(prefill_chunk) < 1:
            raise ValueError("prefill_chunk must be a positive token count")
        self.prefill_chunk = None if prefill_chunk is None else int(prefill_chunk)
        self.block_size = int(block_size)
        self.max_batch = int(max_batch)
        self.eos_token_id = eos_token_id
        self._n_layers = cfg.num_hidden_layers
        self._nkv = cfg.num_key_value_heads
        self._head_dim = cfg.hidden_size // cfg.num_attention_heads

        self._pool_sharding = None
        if mesh is not None:
            from paddle_tpu.distributed.auto_parallel import ProcessMesh
            from paddle_tpu.models.llama import shard_llama

            if not isinstance(mesh, ProcessMesh):
                mesh = ProcessMesh(mesh)
            if mp_axis not in mesh.dim_names:
                raise ValueError(
                    f"mesh has no {mp_axis!r} axis: {mesh.dim_names}")
            shard_llama(model, mesh, mp_axis=mp_axis)
            mp = mesh.get_dim_size(mp_axis)
            from jax.sharding import NamedSharding, PartitionSpec

            if self._nkv % mp == 0:
                # pool pages sharded over KV heads: each mp rank holds its
                # heads' pages; the paged-attention gather stays local
                self._pool_sharding = NamedSharding(
                    mesh.jax_mesh, PartitionSpec(None, mp_axis))
            else:
                import warnings

                warnings.warn(
                    f"num_key_value_heads={self._nkv} not divisible by "
                    f"mp={mp}; KV pool replicated", stacklevel=2)
                self._pool_sharding = NamedSharding(
                    mesh.jax_mesh, PartitionSpec())
        self.mesh = mesh

        # pool pages [num_blocks, Nkv, bs, H] per layer, plus one dedicated
        # scratch page per slot (masked lanes write there, never the pool)
        self._num_blocks = int(num_blocks)
        total = self._num_blocks + self.max_batch
        dt = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self._kpools = [
            jnp.zeros((total, self._nkv, self.block_size, self._head_dim), dt)
            for _ in range(self._n_layers)
        ]
        self._vpools = [jnp.zeros_like(k) for k in self._kpools]
        if self._pool_sharding is not None:
            self._kpools = [jax.device_put(k, self._pool_sharding) for k in self._kpools]
            self._vpools = [jax.device_put(v, self._pool_sharding) for v in self._vpools]
        self._free = list(range(self._num_blocks))
        self._scratch = [self._num_blocks + i for i in range(self.max_batch)]
        self._slots = [_Slot() for _ in range(self.max_batch)]
        self._results: dict = {}
        self._max_blocks_per_seq = max(2, self._num_blocks // max(1, self.max_batch))
        if decode_chunk is not None and int(decode_chunk) < 1:
            raise ValueError("decode_chunk must be >= 1")
        self._decode_chunk = None if decode_chunk is None else int(decode_chunk)
        self._step_fns: dict = {}  # macro-step executables, keyed by D
        # masked lanes' block tables (every page is the slot's scratch
        # page): constant, so committed to the device ONCE here — not
        # re-transferred on every dispatch
        self._scratch_tables = jnp.asarray(np.tile(
            np.asarray(self._scratch, np.int32)[:, None],
            (1, self._max_blocks_per_seq)))
        self._req_counter = 0
        self._state = list(model.state_dict().values())
        _ENGINES.add(self)

        # ---- speculative tier: draft model + its own paged pools --------
        self.draft_model = draft_model
        self.num_speculative = int(num_speculative_tokens)
        self._draft_fn = self._verify_fn = None
        if draft_model is not None:
            if self.num_speculative < 1:
                raise ValueError("num_speculative_tokens must be >= 1")
            dc = draft_model.config
            if dc.vocab_size != cfg.vocab_size:
                raise ValueError("draft and target must share a vocabulary")
            if mesh is not None:
                raise ValueError(
                    "speculative decoding is not combined with the "
                    "tensor-parallel mesh engine yet")
            self._d_layers = dc.num_hidden_layers
            self._d_nkv = dc.num_key_value_heads
            self._d_hd = dc.hidden_size // dc.num_attention_heads
            ddt = jnp.bfloat16 if dc.dtype == "bfloat16" else jnp.float32
            self._d_kpools = [
                jnp.zeros((total, self._d_nkv, self.block_size, self._d_hd), ddt)
                for _ in range(self._d_layers)
            ]
            self._d_vpools = [jnp.zeros_like(k) for k in self._d_kpools]
            self._d_state = list(draft_model.state_dict().values())
            self._spec_stats = {"ticks": 0, "proposed": 0, "accepted": 0,
                                "emitted": 0}

    # ------------------------------------------------------------ requests
    def has_work(self):
        return any(s.active for s in self._slots)

    def result(self, rid):
        return self._results.get(rid)

    def _alloc(self, n):
        if len(self._free) < n:
            raise RuntimeError(
                f"paged pool exhausted: need {n} blocks, {len(self._free)} free"
            )
        out = [self._free.pop() for _ in range(n)]
        return out

    def _release(self, slot):
        self._free.extend(slot.blocks)
        slot.blocks = []
        slot.active = False
        slot.rid = None

    def add_request(self, rid, prompt_ids, max_new_tokens=16,
                    temperature=None, seed=0):
        """Prefill the prompt, pour K/V into pool pages, occupy a slot.

        temperature: None/0 -> greedy decode for this request;
        > 0 -> per-request temperature sampling, deterministic per
        (seed, join order) — the seed is folded with a per-request nonce so
        same-seed requests still draw distinct streams, and each request
        folds its OWN generated-token counter per step.  Requests with
        different decode configs share the ONE compiled decode program
        (the config rides in as per-slot arrays)."""
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import _model_forward_cached

        if self.draft_model is not None and float(temperature or 0.0) > 0.0:
            # checked BEFORE any allocation/prefill: a rejected request
            # must not leak pool blocks or burn two prefills
            raise ValueError(
                "speculative decoding slots are greedy-only (sampled "
                "acceptance needs rejection sampling); drop temperature")
        slot = next((s for s in self._slots if not s.active), None)
        if slot is None:
            raise RuntimeError("no free decode slot; call step() until one drains")
        prompt = np.asarray(prompt_ids, np.int32).reshape(1, -1)
        s0 = prompt.shape[1]
        max_len = s0 + int(max_new_tokens)
        # speculative verify overshoots by up to K+1 positions past the
        # budget before lens bookkeeping rolls back — those writes must
        # land in pages the request OWNS, never in the table-padding block
        headroom = 0 if self.draft_model is None else self.num_speculative + 1
        n_blocks = -(-(max_len + headroom) // self.block_size)
        if n_blocks > self._max_blocks_per_seq:
            raise RuntimeError(
                f"request needs {n_blocks} blocks > per-seq table width "
                f"{self._max_blocks_per_seq}"
            )
        blocks = self._alloc(n_blocks)

        model = self.model
        empty = [
            (
                paddle.zeros([1, 0, self._nkv, self._head_dim], dtype=model.config.dtype),
                paddle.zeros([1, 0, self._nkv, self._head_dim], dtype=model.config.dtype),
            )
            for _ in range(self._n_layers)
        ]
        with paddle.no_grad():
            if self.prefill_chunk is None or s0 <= self.prefill_chunk:
                h, caches = _model_forward_cached(
                    model.model, paddle.to_tensor(prompt), empty, 0)
            else:
                # chunked prefill: fixed-size chunks through the cached
                # forward (bottom-right-aligned cross-length attention)
                # cap the peak activation footprint for long prompts
                caches, off = empty, 0
                while off < s0:
                    chunk = prompt[:, off:off + self.prefill_chunk]
                    h, caches = _model_forward_cached(
                        model.model, paddle.to_tensor(chunk), caches, off)
                    off += chunk.shape[1]
            logits_last = model._logits(h[:, -1:, :])._value[0, -1, :]
            first = int(np.asarray(jnp.argmax(logits_last)))

        # pour prefill K/V into this request's pages
        self._pour(self._kpools, self._vpools, caches, blocks, s0,
                   self._nkv, self._head_dim, sharded=True)
        if self.draft_model is not None:
            # draft prefill over the same prompt into the draft pools
            d_empty = [
                (paddle.zeros([1, 0, self._d_nkv, self._d_hd],
                              dtype=self.draft_model.config.dtype),
                 paddle.zeros([1, 0, self._d_nkv, self._d_hd],
                              dtype=self.draft_model.config.dtype))
                for _ in range(self._d_layers)
            ]
            with paddle.no_grad():
                _, d_caches = _model_forward_cached(
                    self.draft_model.model, paddle.to_tensor(prompt),
                    d_empty, 0)
            self._pour(self._d_kpools, self._d_vpools, d_caches, blocks,
                       s0, self._d_nkv, self._d_hd)
            slot.d_seq_len = s0

        slot.rid = rid
        slot.active = True
        slot.seq_len = s0
        slot.max_len = max_len
        slot.blocks = blocks
        slot.temperature = float(temperature or 0.0)
        # seed folded with a request nonce: same-seed requests get distinct
        # streams; computed ONCE here, not per decode tick
        nonce = self._req_counter
        self._req_counter += 1
        slot.key = np.asarray(
            jax.random.fold_in(jax.random.PRNGKey(int(seed)), nonce))
        if slot.temperature > 0.0:
            # re-pick the FIRST token by sampling (prefill used argmax);
            # fold index 0 = this request's first generated token
            lg = logits_last.astype(jnp.float32) / slot.temperature
            key = jax.random.fold_in(jnp.asarray(slot.key), 0)
            first = int(np.asarray(jax.random.categorical(key, lg)))
        slot.last_token = first
        slot.generated = [first]
        self._results[rid] = slot.generated
        if self.eos_token_id is not None and first == self.eos_token_id:
            self._finish(slot)
        elif slot.seq_len + 1 >= slot.max_len:
            self._finish(slot)
        return first

    def _pour(self, kpools, vpools, caches, blocks, s0, nkv, head_dim,
              sharded=False):
        """Scatter naive prefill caches into a request's pool pages."""
        bs = self.block_size
        n_blocks = len(blocks)
        pad = n_blocks * bs - s0
        for li, (k, v) in enumerate(caches):
            kv = jnp.moveaxis(k._value, 1, 2)  # [1, Nkv, S, H]
            vv = jnp.moveaxis(v._value, 1, 2)
            if pad:
                kv = jnp.pad(kv, ((0, 0), (0, 0), (0, pad), (0, 0)))
                vv = jnp.pad(vv, ((0, 0), (0, 0), (0, pad), (0, 0)))
            # [1, Nkv, n_blocks*bs, H] -> n_blocks x [Nkv, bs, H]
            kv = kv.reshape(nkv, n_blocks, bs, head_dim).swapaxes(0, 1)
            vv = vv.reshape(nkv, n_blocks, bs, head_dim).swapaxes(0, 1)
            idx = jnp.asarray(blocks, jnp.int32)
            kpools[li] = kpools[li].at[idx].set(kv.astype(kpools[li].dtype))
            vpools[li] = vpools[li].at[idx].set(vv.astype(vpools[li].dtype))
            if sharded and self._pool_sharding is not None:
                # keep the pool committed to its head-sharded layout so the
                # decode executable's input shardings stay stable
                kpools[li] = jax.device_put(kpools[li], self._pool_sharding)
                vpools[li] = jax.device_put(vpools[li], self._pool_sharding)

    def _finish(self, slot):
        self._results[slot.rid] = list(slot.generated)
        self._release(slot)

    # -------------------------------------------------------------- decode
    def _effective_chunk(self) -> int:
        if self._decode_chunk is not None:
            return self._decode_chunk
        return max(1, int(_flags.flag("FLAGS_decode_chunk")))

    def _build_step(self, chunk: int):
        """One macro-step executable: `chunk` decode tokens per dispatch.

        The single-token step rides a lax.scan INSIDE the jit (pools
        donated), emitting [B, chunk] tokens per dispatch — one host
        round-trip and one device sync amortize over the whole chunk.
        Rows that hit a stop condition mid-chunk flip a `done` mask: their
        remaining writes land on their scratch page (never the shared
        pool) and their lens/fold counters freeze, so the live rows'
        streams stay bit-identical to the per-token path while the host
        discards the masked tail after the dispatch."""
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import (_decode_layers_paged,
                                             _pool_carry, _pool_unpack)

        model = self.model
        state = self._state
        eos = self.eos_token_id

        def step(state_vals, kpools, vpools, tokens, tables, scratch_tables,
                 lens, max_lens, done0, temps, keys, steps):
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                # carry form ONCE per dispatch: a LayerStack's pools scan
                # as one stacked [N, ...] buffer each — the N-pool concat
                # is paid per dispatch, never per decoded token
                kpools, vpools = _pool_carry(model.model.layers,
                                             kpools, vpools)

                # the body is defined INSIDE the traced step: lax.scan
                # caches body jaxprs by the body's identity, and a shared
                # body would leak one trace's bound-weight tracers into
                # the next trace
                def one(carry, _):
                    tok, kps, vps, lens_c, steps_c, done = carry
                    # finished/inactive lanes park on their scratch page
                    # with lens 1 — same geometry the host gives inactive
                    # slots, so their writes never touch the shared pool
                    tables_eff = jnp.where(done[:, None], scratch_tables,
                                           tables)
                    lens_eff = jnp.where(done, jnp.int32(1), lens_c)
                    with no_grad():
                        h = model.model.embed_tokens(Tensor(tok))
                        cos = model.model.rope_cos._value
                        sin = model.model.rope_sin._value
                        h, kps, vps = _decode_layers_paged(
                            model.model.layers, h, cos, sin, kps, vps,
                            tables_eff, lens_eff)
                        h = model.model.norm(h)
                        logits = model._logits(h)
                    lg = logits._value[:, -1, :]
                    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    # per-slot temperature sampling inside the SAME
                    # program: fold the slot's generated-token counter
                    # into its key, sample per row, select by the mask
                    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
                    skeys = jax.vmap(jax.random.fold_in)(keys, steps_c)
                    sampled = jax.vmap(jax.random.categorical)(
                        skeys, lg.astype(jnp.float32) / safe_t
                    ).astype(jnp.int32)
                    nxt = jnp.where(temps > 0, sampled, greedy)
                    # mirror of the host stop conditions: EOS, or the
                    # sequence (now lens_c long) leaving no room for one
                    # more token within max_len
                    fin = ((nxt == eos) if eos is not None
                           else jnp.zeros_like(done))
                    new_done = done | fin | (lens_c + 1 >= max_lens)
                    lens_n = jnp.where(done, lens_c, lens_c + 1)
                    steps_n = jnp.where(done, steps_c, steps_c + 1)
                    return (nxt[:, None], kps, vps, lens_n, steps_n,
                            new_done), nxt

                (tok, kpools, vpools, *_), toks = jax.lax.scan(
                    one, (tokens, kpools, vpools, lens, steps, done0),
                    None, length=chunk)
                kpools, vpools = _pool_unpack(model.model.layers,
                                              kpools, vpools)
                return jnp.moveaxis(toks, 0, 1), kpools, vpools
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(step, donate_argnums=(1, 2))

    def _build_draft_step(self):
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import _decode_layers_paged

        model = self.draft_model
        state = self._d_state

        def dstep(state_vals, kpools, vpools, tokens, tables, lens):
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                with no_grad():
                    h = model.model.embed_tokens(Tensor(tokens))
                    cos = model.model.rope_cos._value
                    sin = model.model.rope_sin._value
                    h, new_k, new_v = _decode_layers_paged(
                        model.model.layers, h, cos, sin, kpools, vpools,
                        tables, lens)
                    h = model.model.norm(h)
                    logits = model._logits(h)
                return (jnp.argmax(logits._value[:, -1, :], axis=-1)
                        .astype(jnp.int32), new_k, new_v)
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(dstep)

    def _build_verify(self):
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import _decode_layers_paged

        model = self.model
        state = self._state

        def verify(state_vals, kpools, vpools, tokens, tables, lens):
            """tokens [B, K+1]; lens INCLUDING the whole chunk; returns
            preds [B, K+1] (greedy next token after each chunk position)
            plus the written pools."""
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                with no_grad():
                    h = model.model.embed_tokens(Tensor(tokens))
                    cos = model.model.rope_cos._value
                    sin = model.model.rope_sin._value
                    h, new_k, new_v = _decode_layers_paged(
                        model.model.layers, h, cos, sin, kpools, vpools,
                        tables, lens, chunk=True)
                    h = model.model.norm(h)
                    logits = model._logits(h)
                return (jnp.argmax(logits._value, axis=-1).astype(jnp.int32),
                        new_k, new_v)
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(verify)

    def _spec_step(self):
        """One speculative tick: the draft proposes K tokens per live slot
        (K compiled single-token draft steps, batched over slots), the
        target verifies every chunk in ONE compiled multi-token step, and
        per-slot greedy acceptance emits 1..K+1 tokens.  Rejected tail
        entries in the pools die by lens bookkeeping — pages are
        positional, so rollback costs nothing."""
        if self._draft_fn is None:
            self._draft_fn = self._build_draft_step()
            self._verify_fn = self._build_verify()
        K = self.num_speculative
        B, W = self.max_batch, self._max_blocks_per_seq
        tables = np.zeros((B, W), np.int32)
        last = np.zeros((B, 1), np.int32)
        seq0 = np.zeros((B,), np.int32)
        d0 = np.zeros((B,), np.int32)
        for i, sl in enumerate(self._slots):
            if sl.active:
                row = list(sl.blocks) + [sl.blocks[-1]] * (W - len(sl.blocks))
                tables[i] = row
                last[i, 0] = sl.last_token
                seq0[i] = sl.seq_len
                d0[i] = sl.d_seq_len
            else:
                tables[i] = self._scratch[i]
        tables_j = jnp.asarray(tables)

        # ---- draft proposes K tokens (inactive lanes ride scratch) -----
        # K+1 draft steps: the extra step feeds the LAST proposal so the
        # draft pool always covers its own proposals — acceptance then
        # never needs a per-slot catch-up pass, whatever gets accepted
        d_state = [t._value for t in self._d_state]
        prop_dev = []
        tok = jnp.asarray(last)
        for j in range(K + 1):
            lens_d = jnp.asarray(d0 + 1 + j)
            tok1, dk, dv = self._draft_fn(
                d_state, list(self._d_kpools), list(self._d_vpools),
                tok, tables_j, lens_d)
            self._d_kpools, self._d_vpools = list(dk), list(dv)
            if j < K:
                prop_dev.append(tok1)
                tok = tok1[:, None]  # stays on device: steps pipeline
        _DECODE_STATS["dispatches"] += K + 1
        t_sync = time.perf_counter()
        proposals = np.stack([np.asarray(t) for t in prop_dev], axis=1)
        _DECODE_STATS["sync_seconds"] += time.perf_counter() - t_sync

        # ---- target verifies the whole chunk in one step ---------------
        chunk = np.concatenate([last, proposals], axis=1)  # [B, K+1]
        lens_v = jnp.asarray(seq0 + K + 1)
        preds, nk, nv = self._verify_fn(
            [t._value for t in self._state],
            list(self._kpools), list(self._vpools),
            jnp.asarray(chunk), tables_j, lens_v)
        self._kpools, self._vpools = list(nk), list(nv)
        _DECODE_STATS["dispatches"] += 1
        t_sync = time.perf_counter()
        preds = np.asarray(preds)  # [B, K+1]
        _DECODE_STATS["sync_seconds"] += time.perf_counter() - t_sync

        # ---- per-slot acceptance + emission ----------------------------
        self._spec_stats["ticks"] += 1
        out = {}
        for i, sl in enumerate(self._slots):
            if not sl.active:
                continue
            accepted = 0
            while accepted < K and preds[i, accepted] == proposals[i, accepted]:
                accepted += 1
            self._spec_stats["proposed"] += K
            self._spec_stats["accepted"] += accepted
            new_toks = [int(t) for t in proposals[i, :accepted]]
            new_toks.append(int(preds[i, accepted]))
            base_seq = sl.seq_len  # pre-round trusted pool coverage
            emitted = []
            finish = False
            for t in new_toks:
                emitted.append(t)
                sl.generated.append(t)
                if self.eos_token_id is not None and t == self.eos_token_id:
                    finish = True
                    break
                # total = prompt + generated = base_seq + 1 + emitted
                if base_seq + 1 + len(emitted) >= sl.max_len:
                    finish = True
                    break
            # trusted pool coverage = prompt + generated[:-1]; the draft
            # pool covers the same prefix (its stale tail dies positionally)
            sl.seq_len = base_seq + len(emitted)
            sl.d_seq_len = sl.seq_len
            sl.last_token = emitted[-1]
            out[sl.rid] = emitted
            self._spec_stats["emitted"] += len(emitted)
            if finish:
                self._finish(sl)
        return out

    def spec_stats(self):
        """Speculative acceptance counters (None on plain engines):
        mean acceptance = accepted/proposed sizes num_speculative_tokens;
        emitted/ticks is the per-tick speedup over plain decode."""
        return None if self.draft_model is None else dict(self._spec_stats)

    def step(self):
        """One macro-step for every live request: D = decode_chunk tokens
        advance in ONE compiled dispatch; requests are admitted/retired
        only here, at macro-step boundaries (stop conditions re-checked on
        the host after the dispatch; a row that stopped mid-chunk had its
        surplus lanes masked onto its scratch page in-device and its
        surplus tokens dropped now).

        Plain engines return {rid: token} when D == 1 and
        {rid: [tok, ...]} when D > 1; SPECULATIVE engines always emit a
        LIST of tokens per request per tick — one accepted run plus the
        target's correction/bonus token."""
        if not self.has_work():
            return {}
        t_start = time.perf_counter()
        if self.draft_model is not None:
            out = self._spec_step()
            _DECODE_STATS["tokens"] += sum(len(v) for v in out.values())
            _DECODE_STATS["macro_steps"] += 1
            _DECODE_STATS["step_seconds"] += time.perf_counter() - t_start
            return out
        D = self._effective_chunk()
        step_fn = self._step_fns.get(D)
        if step_fn is None:
            step_fn = self._step_fns[D] = self._build_step(D)

        B, W = self.max_batch, self._max_blocks_per_seq
        tokens = np.zeros((B, 1), np.int32)
        tables = np.zeros((B, W), np.int32)
        lens = np.ones((B,), np.int32)
        max_lens = np.zeros((B,), np.int32)
        done0 = np.ones((B,), bool)
        temps = np.zeros((B,), np.float32)
        keys = np.zeros((B, 2), np.uint32)
        steps = np.zeros((B,), np.uint32)
        for i, s in enumerate(self._slots):
            if s.active:
                tokens[i, 0] = s.last_token
                row = list(s.blocks) + [s.blocks[-1]] * (W - len(s.blocks))
                tables[i] = row
                lens[i] = s.seq_len + 1  # includes the token being decoded
                max_lens[i] = s.max_len
                done0[i] = False
                temps[i] = s.temperature
                keys[i] = s.key
                steps[i] = len(s.generated)  # fold index for this request
            else:
                tables[i] = self._scratch[i]  # park masked lanes off-pool
                lens[i] = 1

        nxt, new_k, new_v = step_fn(
            [t._value for t in self._state],
            list(self._kpools), list(self._vpools),
            jnp.asarray(tokens), jnp.asarray(tables),
            self._scratch_tables, jnp.asarray(lens),
            jnp.asarray(max_lens), jnp.asarray(done0),
            jnp.asarray(temps), jnp.asarray(keys), jnp.asarray(steps),
        )
        self._kpools = list(new_k)
        self._vpools = list(new_v)
        t_sync = time.perf_counter()
        nxt = np.asarray(nxt)  # [B, D] — the one device sync per chunk
        _DECODE_STATS["dispatches"] += 1
        _DECODE_STATS["macro_steps"] += 1
        _DECODE_STATS["last_chunk"] = D
        _DECODE_STATS["sync_seconds"] += time.perf_counter() - t_sync

        out = {}
        for i, s in enumerate(self._slots):
            if not s.active:
                continue
            rid = s.rid  # _finish() clears the slot's rid on retirement
            emitted = []
            for j in range(D):
                tok = int(nxt[i, j])
                s.seq_len += 1
                s.last_token = tok
                s.generated.append(tok)
                emitted.append(tok)
                if (self.eos_token_id is not None
                        and tok == self.eos_token_id) or (
                            s.seq_len + 1 >= s.max_len):
                    self._finish(s)
                    break
            out[rid] = emitted if D > 1 else emitted[0]
            _DECODE_STATS["tokens"] += len(emitted)
        _DECODE_STATS["step_seconds"] += time.perf_counter() - t_start
        return out
