"""Continuous-batching generation engine over the paged-KV tier.

Reference lineage: the block-attention serving stack —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and the
FastDeploy/PaddleNLP continuous-batching servers built on it (requests
share one block pool through per-request block tables, joining and leaving
the decode batch between steps).

TPU-native design: the decode batch has a FIXED number of slots, so every
step — any mix of live requests — reuses ONE compiled XLA program (static
shapes are the whole game on TPU; the reference's GPU kernel re-launches
per ragged batch instead).  A host-side block allocator hands pool pages
to requests and recycles them at completion; inactive slots park on a
dedicated scratch page each so the shared pool is never corrupted by
masked lanes.  Prefill runs per admitted request and pours its K/V into
pool pages; decode then advances all live slots together.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["GenerationEngine"]


@dataclass
class _Slot:
    rid: object = None
    active: bool = False
    seq_len: int = 0          # tokens stored in the pool (incl. prompt)
    max_len: int = 0          # seq_len limit for this request
    blocks: list = field(default_factory=list)
    last_token: int = 0
    generated: list = field(default_factory=list)
    # per-request decode config (temperature-sampling tier; 0 = greedy)
    temperature: float = 0.0
    key: object = None        # precomputed PRNG key (seed + request nonce)
    d_seq_len: int = 0        # draft-pool coverage (speculative tier)


class GenerationEngine:
    """Greedy continuous-batching decode over a shared paged-KV pool.

    Usage:
        eng = GenerationEngine(model, max_batch=4, block_size=16, num_blocks=64)
        eng.add_request("a", prompt_ids_a, max_new_tokens=8)
        while eng.has_work():
            for rid, tok in eng.step().items(): ...
        eng.result("a")  # -> list of generated token ids
    """

    def __init__(self, model, max_batch=4, block_size=16, num_blocks=128,
                 eos_token_id=None, mesh=None, mp_axis="mp",
                 prefill_chunk=None, draft_model=None,
                 num_speculative_tokens=4):
        """mesh: optional ProcessMesh/jax Mesh with an `mp_axis` dimension —
        the engine then serves TENSOR-PARALLEL: weights get Megatron
        placements (models.llama.shard_llama), the paged-KV pool is sharded
        over the KV-head dim, and the ONE compiled decode program runs
        GSPMD-partitioned over the mesh (VERDICT r3 #6; reference capability:
        analysis_predictor multi-device serving)."""
        cfg = model.config
        self.model = model
        if prefill_chunk is not None and int(prefill_chunk) < 1:
            raise ValueError("prefill_chunk must be a positive token count")
        self.prefill_chunk = None if prefill_chunk is None else int(prefill_chunk)
        self.block_size = int(block_size)
        self.max_batch = int(max_batch)
        self.eos_token_id = eos_token_id
        self._n_layers = cfg.num_hidden_layers
        self._nkv = cfg.num_key_value_heads
        self._head_dim = cfg.hidden_size // cfg.num_attention_heads

        self._pool_sharding = None
        if mesh is not None:
            from paddle_tpu.distributed.auto_parallel import ProcessMesh
            from paddle_tpu.models.llama import shard_llama

            if not isinstance(mesh, ProcessMesh):
                mesh = ProcessMesh(mesh)
            if mp_axis not in mesh.dim_names:
                raise ValueError(
                    f"mesh has no {mp_axis!r} axis: {mesh.dim_names}")
            shard_llama(model, mesh, mp_axis=mp_axis)
            mp = mesh.get_dim_size(mp_axis)
            from jax.sharding import NamedSharding, PartitionSpec

            if self._nkv % mp == 0:
                # pool pages sharded over KV heads: each mp rank holds its
                # heads' pages; the paged-attention gather stays local
                self._pool_sharding = NamedSharding(
                    mesh.jax_mesh, PartitionSpec(None, mp_axis))
            else:
                import warnings

                warnings.warn(
                    f"num_key_value_heads={self._nkv} not divisible by "
                    f"mp={mp}; KV pool replicated", stacklevel=2)
                self._pool_sharding = NamedSharding(
                    mesh.jax_mesh, PartitionSpec())
        self.mesh = mesh

        # pool pages [num_blocks, Nkv, bs, H] per layer, plus one dedicated
        # scratch page per slot (masked lanes write there, never the pool)
        self._num_blocks = int(num_blocks)
        total = self._num_blocks + self.max_batch
        dt = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self._kpools = [
            jnp.zeros((total, self._nkv, self.block_size, self._head_dim), dt)
            for _ in range(self._n_layers)
        ]
        self._vpools = [jnp.zeros_like(k) for k in self._kpools]
        if self._pool_sharding is not None:
            self._kpools = [jax.device_put(k, self._pool_sharding) for k in self._kpools]
            self._vpools = [jax.device_put(v, self._pool_sharding) for v in self._vpools]
        self._free = list(range(self._num_blocks))
        self._scratch = [self._num_blocks + i for i in range(self.max_batch)]
        self._slots = [_Slot() for _ in range(self.max_batch)]
        self._results: dict = {}
        self._max_blocks_per_seq = max(2, self._num_blocks // max(1, self.max_batch))
        self._step_fn = None
        self._req_counter = 0
        self._state = list(model.state_dict().values())

        # ---- speculative tier: draft model + its own paged pools --------
        self.draft_model = draft_model
        self.num_speculative = int(num_speculative_tokens)
        self._draft_fn = self._verify_fn = None
        if draft_model is not None:
            if self.num_speculative < 1:
                raise ValueError("num_speculative_tokens must be >= 1")
            dc = draft_model.config
            if dc.vocab_size != cfg.vocab_size:
                raise ValueError("draft and target must share a vocabulary")
            if mesh is not None:
                raise ValueError(
                    "speculative decoding is not combined with the "
                    "tensor-parallel mesh engine yet")
            self._d_layers = dc.num_hidden_layers
            self._d_nkv = dc.num_key_value_heads
            self._d_hd = dc.hidden_size // dc.num_attention_heads
            ddt = jnp.bfloat16 if dc.dtype == "bfloat16" else jnp.float32
            self._d_kpools = [
                jnp.zeros((total, self._d_nkv, self.block_size, self._d_hd), ddt)
                for _ in range(self._d_layers)
            ]
            self._d_vpools = [jnp.zeros_like(k) for k in self._d_kpools]
            self._d_state = list(draft_model.state_dict().values())
            self._spec_stats = {"ticks": 0, "proposed": 0, "accepted": 0,
                                "emitted": 0}

    # ------------------------------------------------------------ requests
    def has_work(self):
        return any(s.active for s in self._slots)

    def result(self, rid):
        return self._results.get(rid)

    def _alloc(self, n):
        if len(self._free) < n:
            raise RuntimeError(
                f"paged pool exhausted: need {n} blocks, {len(self._free)} free"
            )
        out = [self._free.pop() for _ in range(n)]
        return out

    def _release(self, slot):
        self._free.extend(slot.blocks)
        slot.blocks = []
        slot.active = False
        slot.rid = None

    def add_request(self, rid, prompt_ids, max_new_tokens=16,
                    temperature=None, seed=0):
        """Prefill the prompt, pour K/V into pool pages, occupy a slot.

        temperature: None/0 -> greedy decode for this request;
        > 0 -> per-request temperature sampling, deterministic per
        (seed, join order) — the seed is folded with a per-request nonce so
        same-seed requests still draw distinct streams, and each request
        folds its OWN generated-token counter per step.  Requests with
        different decode configs share the ONE compiled decode program
        (the config rides in as per-slot arrays)."""
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import _model_forward_cached

        if self.draft_model is not None and float(temperature or 0.0) > 0.0:
            # checked BEFORE any allocation/prefill: a rejected request
            # must not leak pool blocks or burn two prefills
            raise ValueError(
                "speculative decoding slots are greedy-only (sampled "
                "acceptance needs rejection sampling); drop temperature")
        slot = next((s for s in self._slots if not s.active), None)
        if slot is None:
            raise RuntimeError("no free decode slot; call step() until one drains")
        prompt = np.asarray(prompt_ids, np.int32).reshape(1, -1)
        s0 = prompt.shape[1]
        max_len = s0 + int(max_new_tokens)
        # speculative verify overshoots by up to K+1 positions past the
        # budget before lens bookkeeping rolls back — those writes must
        # land in pages the request OWNS, never in the table-padding block
        headroom = 0 if self.draft_model is None else self.num_speculative + 1
        n_blocks = -(-(max_len + headroom) // self.block_size)
        if n_blocks > self._max_blocks_per_seq:
            raise RuntimeError(
                f"request needs {n_blocks} blocks > per-seq table width "
                f"{self._max_blocks_per_seq}"
            )
        blocks = self._alloc(n_blocks)

        model = self.model
        empty = [
            (
                paddle.zeros([1, 0, self._nkv, self._head_dim], dtype=model.config.dtype),
                paddle.zeros([1, 0, self._nkv, self._head_dim], dtype=model.config.dtype),
            )
            for _ in range(self._n_layers)
        ]
        with paddle.no_grad():
            if self.prefill_chunk is None or s0 <= self.prefill_chunk:
                h, caches = _model_forward_cached(
                    model.model, paddle.to_tensor(prompt), empty, 0)
            else:
                # chunked prefill: fixed-size chunks through the cached
                # forward (bottom-right-aligned cross-length attention)
                # cap the peak activation footprint for long prompts
                caches, off = empty, 0
                while off < s0:
                    chunk = prompt[:, off:off + self.prefill_chunk]
                    h, caches = _model_forward_cached(
                        model.model, paddle.to_tensor(chunk), caches, off)
                    off += chunk.shape[1]
            logits_last = model._logits(h[:, -1:, :])._value[0, -1, :]
            first = int(np.asarray(jnp.argmax(logits_last)))

        # pour prefill K/V into this request's pages
        self._pour(self._kpools, self._vpools, caches, blocks, s0,
                   self._nkv, self._head_dim, sharded=True)
        if self.draft_model is not None:
            # draft prefill over the same prompt into the draft pools
            d_empty = [
                (paddle.zeros([1, 0, self._d_nkv, self._d_hd],
                              dtype=self.draft_model.config.dtype),
                 paddle.zeros([1, 0, self._d_nkv, self._d_hd],
                              dtype=self.draft_model.config.dtype))
                for _ in range(self._d_layers)
            ]
            with paddle.no_grad():
                _, d_caches = _model_forward_cached(
                    self.draft_model.model, paddle.to_tensor(prompt),
                    d_empty, 0)
            self._pour(self._d_kpools, self._d_vpools, d_caches, blocks,
                       s0, self._d_nkv, self._d_hd)
            slot.d_seq_len = s0

        slot.rid = rid
        slot.active = True
        slot.seq_len = s0
        slot.max_len = max_len
        slot.blocks = blocks
        slot.temperature = float(temperature or 0.0)
        # seed folded with a request nonce: same-seed requests get distinct
        # streams; computed ONCE here, not per decode tick
        nonce = self._req_counter
        self._req_counter += 1
        slot.key = np.asarray(
            jax.random.fold_in(jax.random.PRNGKey(int(seed)), nonce))
        if slot.temperature > 0.0:
            # re-pick the FIRST token by sampling (prefill used argmax);
            # fold index 0 = this request's first generated token
            lg = logits_last.astype(jnp.float32) / slot.temperature
            key = jax.random.fold_in(jnp.asarray(slot.key), 0)
            first = int(np.asarray(jax.random.categorical(key, lg)))
        slot.last_token = first
        slot.generated = [first]
        self._results[rid] = slot.generated
        if self.eos_token_id is not None and first == self.eos_token_id:
            self._finish(slot)
        elif slot.seq_len + 1 >= slot.max_len:
            self._finish(slot)
        return first

    def _pour(self, kpools, vpools, caches, blocks, s0, nkv, head_dim,
              sharded=False):
        """Scatter naive prefill caches into a request's pool pages."""
        bs = self.block_size
        n_blocks = len(blocks)
        pad = n_blocks * bs - s0
        for li, (k, v) in enumerate(caches):
            kv = jnp.moveaxis(k._value, 1, 2)  # [1, Nkv, S, H]
            vv = jnp.moveaxis(v._value, 1, 2)
            if pad:
                kv = jnp.pad(kv, ((0, 0), (0, 0), (0, pad), (0, 0)))
                vv = jnp.pad(vv, ((0, 0), (0, 0), (0, pad), (0, 0)))
            # [1, Nkv, n_blocks*bs, H] -> n_blocks x [Nkv, bs, H]
            kv = kv.reshape(nkv, n_blocks, bs, head_dim).swapaxes(0, 1)
            vv = vv.reshape(nkv, n_blocks, bs, head_dim).swapaxes(0, 1)
            idx = jnp.asarray(blocks, jnp.int32)
            kpools[li] = kpools[li].at[idx].set(kv.astype(kpools[li].dtype))
            vpools[li] = vpools[li].at[idx].set(vv.astype(vpools[li].dtype))
            if sharded and self._pool_sharding is not None:
                # keep the pool committed to its head-sharded layout so the
                # decode executable's input shardings stay stable
                kpools[li] = jax.device_put(kpools[li], self._pool_sharding)
                vpools[li] = jax.device_put(vpools[li], self._pool_sharding)

    def _finish(self, slot):
        self._results[slot.rid] = list(slot.generated)
        self._release(slot)

    # -------------------------------------------------------------- decode
    def _build_step(self):
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import _decode_layer_paged

        model = self.model
        state = self._state

        def step(state_vals, kpools, vpools, tokens, tables, lens, temps, keys, steps):
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                with no_grad():
                    h = model.model.embed_tokens(Tensor(tokens))
                    cos = model.model.rope_cos._value
                    sin = model.model.rope_sin._value
                    new_k, new_v = [], []
                    for li, layer in enumerate(model.model.layers):
                        h, kc, vc = _decode_layer_paged(
                            layer, h, cos, sin, kpools[li], vpools[li], tables, lens
                        )
                        new_k.append(kc)
                        new_v.append(vc)
                    h = model.model.norm(h)
                    logits = model._logits(h)
                lg = logits._value[:, -1, :]
                greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                # per-slot temperature sampling inside the SAME program:
                # fold the step index into each slot's key, sample per row,
                # select sampled vs greedy by the per-slot mask
                safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
                # each slot folds its OWN generated-token counter
                skeys = jax.vmap(jax.random.fold_in)(keys, steps)
                sampled = jax.vmap(jax.random.categorical)(
                    skeys, lg.astype(jnp.float32) / safe_t).astype(jnp.int32)
                nxt = jnp.where(temps > 0, sampled, greedy)
                return nxt, new_k, new_v
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(step)

    def _build_draft_step(self):
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import _decode_layer_paged

        model = self.draft_model
        state = self._d_state

        def dstep(state_vals, kpools, vpools, tokens, tables, lens):
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                with no_grad():
                    h = model.model.embed_tokens(Tensor(tokens))
                    cos = model.model.rope_cos._value
                    sin = model.model.rope_sin._value
                    new_k, new_v = [], []
                    for li, layer in enumerate(model.model.layers):
                        h, kc, vc = _decode_layer_paged(
                            layer, h, cos, sin, kpools[li], vpools[li],
                            tables, lens)
                        new_k.append(kc)
                        new_v.append(vc)
                    h = model.model.norm(h)
                    logits = model._logits(h)
                return (jnp.argmax(logits._value[:, -1, :], axis=-1)
                        .astype(jnp.int32), new_k, new_v)
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(dstep)

    def _build_verify(self):
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import _decode_layer_paged_chunk

        model = self.model
        state = self._state

        def verify(state_vals, kpools, vpools, tokens, tables, lens):
            """tokens [B, K+1]; lens INCLUDING the whole chunk; returns
            preds [B, K+1] (greedy next token after each chunk position)
            plus the written pools."""
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                with no_grad():
                    h = model.model.embed_tokens(Tensor(tokens))
                    cos = model.model.rope_cos._value
                    sin = model.model.rope_sin._value
                    new_k, new_v = [], []
                    for li, layer in enumerate(model.model.layers):
                        h, kc, vc = _decode_layer_paged_chunk(
                            layer, h, cos, sin, kpools[li], vpools[li],
                            tables, lens)
                        new_k.append(kc)
                        new_v.append(vc)
                    h = model.model.norm(h)
                    logits = model._logits(h)
                return (jnp.argmax(logits._value, axis=-1).astype(jnp.int32),
                        new_k, new_v)
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(verify)

    def _spec_step(self):
        """One speculative tick: the draft proposes K tokens per live slot
        (K compiled single-token draft steps, batched over slots), the
        target verifies every chunk in ONE compiled multi-token step, and
        per-slot greedy acceptance emits 1..K+1 tokens.  Rejected tail
        entries in the pools die by lens bookkeeping — pages are
        positional, so rollback costs nothing."""
        if self._draft_fn is None:
            self._draft_fn = self._build_draft_step()
            self._verify_fn = self._build_verify()
        K = self.num_speculative
        B, W = self.max_batch, self._max_blocks_per_seq
        tables = np.zeros((B, W), np.int32)
        last = np.zeros((B, 1), np.int32)
        seq0 = np.zeros((B,), np.int32)
        d0 = np.zeros((B,), np.int32)
        for i, sl in enumerate(self._slots):
            if sl.active:
                row = list(sl.blocks) + [sl.blocks[-1]] * (W - len(sl.blocks))
                tables[i] = row
                last[i, 0] = sl.last_token
                seq0[i] = sl.seq_len
                d0[i] = sl.d_seq_len
            else:
                tables[i] = self._scratch[i]
        tables_j = jnp.asarray(tables)

        # ---- draft proposes K tokens (inactive lanes ride scratch) -----
        # K+1 draft steps: the extra step feeds the LAST proposal so the
        # draft pool always covers its own proposals — acceptance then
        # never needs a per-slot catch-up pass, whatever gets accepted
        d_state = [t._value for t in self._d_state]
        prop_dev = []
        tok = jnp.asarray(last)
        for j in range(K + 1):
            lens_d = jnp.asarray(d0 + 1 + j)
            tok1, dk, dv = self._draft_fn(
                d_state, list(self._d_kpools), list(self._d_vpools),
                tok, tables_j, lens_d)
            self._d_kpools, self._d_vpools = list(dk), list(dv)
            if j < K:
                prop_dev.append(tok1)
                tok = tok1[:, None]  # stays on device: steps pipeline
        proposals = np.stack([np.asarray(t) for t in prop_dev], axis=1)

        # ---- target verifies the whole chunk in one step ---------------
        chunk = np.concatenate([last, proposals], axis=1)  # [B, K+1]
        lens_v = jnp.asarray(seq0 + K + 1)
        preds, nk, nv = self._verify_fn(
            [t._value for t in self._state],
            list(self._kpools), list(self._vpools),
            jnp.asarray(chunk), tables_j, lens_v)
        self._kpools, self._vpools = list(nk), list(nv)
        preds = np.asarray(preds)  # [B, K+1]

        # ---- per-slot acceptance + emission ----------------------------
        self._spec_stats["ticks"] += 1
        out = {}
        for i, sl in enumerate(self._slots):
            if not sl.active:
                continue
            accepted = 0
            while accepted < K and preds[i, accepted] == proposals[i, accepted]:
                accepted += 1
            self._spec_stats["proposed"] += K
            self._spec_stats["accepted"] += accepted
            new_toks = [int(t) for t in proposals[i, :accepted]]
            new_toks.append(int(preds[i, accepted]))
            base_seq = sl.seq_len  # pre-round trusted pool coverage
            emitted = []
            finish = False
            for t in new_toks:
                emitted.append(t)
                sl.generated.append(t)
                if self.eos_token_id is not None and t == self.eos_token_id:
                    finish = True
                    break
                # total = prompt + generated = base_seq + 1 + emitted
                if base_seq + 1 + len(emitted) >= sl.max_len:
                    finish = True
                    break
            # trusted pool coverage = prompt + generated[:-1]; the draft
            # pool covers the same prefix (its stale tail dies positionally)
            sl.seq_len = base_seq + len(emitted)
            sl.d_seq_len = sl.seq_len
            sl.last_token = emitted[-1]
            out[sl.rid] = emitted
            self._spec_stats["emitted"] += len(emitted)
            if finish:
                self._finish(sl)
        return out

    def spec_stats(self):
        """Speculative acceptance counters (None on plain engines):
        mean acceptance = accepted/proposed sizes num_speculative_tokens;
        emitted/ticks is the per-tick speedup over plain decode."""
        return None if self.draft_model is None else dict(self._spec_stats)

    def step(self):
        """One decode tick for every live request.

        Plain engines return {rid: token}; SPECULATIVE engines emit a
        LIST of tokens per request per tick ({rid: [tok, ...]}) — one
        accepted run plus the target's correction/bonus token."""
        if not self.has_work():
            return {}
        if self.draft_model is not None:
            return self._spec_step()
        if self._step_fn is None:
            self._step_fn = self._build_step()

        B, W = self.max_batch, self._max_blocks_per_seq
        tokens = np.zeros((B, 1), np.int32)
        tables = np.zeros((B, W), np.int32)
        lens = np.ones((B,), np.int32)
        temps = np.zeros((B,), np.float32)
        keys = np.zeros((B, 2), np.uint32)
        steps = np.zeros((B,), np.uint32)
        for i, s in enumerate(self._slots):
            if s.active:
                tokens[i, 0] = s.last_token
                row = list(s.blocks) + [s.blocks[-1]] * (W - len(s.blocks))
                tables[i] = row
                lens[i] = s.seq_len + 1  # includes the token being decoded
                temps[i] = s.temperature
                keys[i] = s.key
                steps[i] = len(s.generated)  # fold index for this request
            else:
                tables[i] = self._scratch[i]  # park masked lanes off-pool
                lens[i] = 1

        nxt, new_k, new_v = self._step_fn(
            [t._value for t in self._state],
            list(self._kpools), list(self._vpools),
            jnp.asarray(tokens), jnp.asarray(tables), jnp.asarray(lens),
            jnp.asarray(temps), jnp.asarray(keys), jnp.asarray(steps),
        )
        self._kpools = list(new_k)
        self._vpools = list(new_v)
        nxt = np.asarray(nxt)

        out = {}
        for i, s in enumerate(self._slots):
            if not s.active:
                continue
            tok = int(nxt[i])
            s.seq_len += 1
            s.last_token = tok
            s.generated.append(tok)
            out[s.rid] = tok
            if (self.eos_token_id is not None and tok == self.eos_token_id) or (
                s.seq_len + 1 >= s.max_len
            ):
                self._finish(s)
        return out
