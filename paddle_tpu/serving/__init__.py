"""Continuous-batching generation engine over the paged-KV tier.

Reference lineage: the block-attention serving stack —
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and the
FastDeploy/PaddleNLP continuous-batching servers built on it (requests
share one block pool through per-request block tables, joining and leaving
the decode batch between steps).

TPU-native design: the decode batch has a FIXED number of slots, so every
step — any mix of live requests — reuses ONE compiled XLA program (static
shapes are the whole game on TPU; the reference's GPU kernel re-launches
per ragged batch instead).  A host-side block allocator hands pool pages
to requests and recycles them at completion; inactive slots park on a
dedicated scratch page each so the shared pool is never corrupted by
masked lanes.  Prefill runs per admitted request and pours its K/V into
pool pages; decode then advances all live slots together.
"""

from __future__ import annotations

import contextlib
import time
import weakref
from collections import deque
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu._core import flags as _flags

__all__ = ["GenerationEngine", "RadixPrefixCache", "decode_stats",
           "reset_decode_stats", "lora_stats", "reset_lora_stats",
           "schedule_decode_stats", "reset_schedule_decode_stats",
           "EngineSnapshot", "restore_engine", "snapshot_stats",
           "reset_snapshot_stats"]


# --------------------------------------------------------- decode telemetry
# Process-wide decode counters (profiler.decode_stats() reads them): one
# dispatch = one compiled-program launch; sync_seconds = host time blocked
# materializing device results (the per-token round-trip macro-stepping
# amortizes); tokens counts EMITTED tokens (masked tail lanes excluded).
_DECODE_STATS = {
    "dispatches": 0,
    "tokens": 0,
    "sync_seconds": 0.0,
    "step_seconds": 0.0,
    "macro_steps": 0,
    "last_chunk": 0,
    # prefix-cache tier (FLAGS_prefix_cache): admissions that reused at
    # least one cached page / that found nothing, prompt tokens whose
    # prefill was AVOIDED by page reuse, and LRU evictions of reclaimable
    # (refcount-zero) cached pages under pool pressure
    "prefix_hits": 0,
    "prefix_misses": 0,
    "prefix_hit_tokens": 0,
    "prefix_evictions": 0,
    # capacity tier: resident bytes of the most recent engine's pools
    # (payload + scales for int8) and the peak concurrently-active
    # requests observed — bytes/resident is the int8-KV capacity metric
    "pool_bytes": 0,
    "resident_peak": 0,
    # sharded-serving tier: the most recent engine's PER-DEVICE pool
    # bytes (each pool leaf's committed sharding divides its global
    # bytes — ops.paged_attention.pool_device_nbytes over pool_parts)
    # and the mesh shape string ("" on single-device engines); the
    # Profiler.summary() serving footer prints both when sharded
    "pool_bytes_per_device": 0,
    "mesh_shape": "",
    # overload-discipline tier (docs/DECODE.md admission scheduler):
    # interleaved prefill chunks run between decode dispatches
    # (FLAGS_prefill_chunk_blocks), LOW-priority preemptions (pages
    # parked host-side) and their re-admissions, the parked-request
    # GAUGE, and the per-priority-class admitted/completed breakdown
    "prefill_chunks": 0,
    "preemptions": 0,
    "preempt_readmits": 0,
    "parked_requests": 0,
    "admitted_high": 0,
    "admitted_normal": 0,
    "admitted_low": 0,
    "completed_high": 0,
    "completed_normal": 0,
    "completed_low": 0,
}


def decode_stats(reset: bool = False) -> dict:
    """Serving decode counters: dispatches, emitted tokens, host sync
    seconds, total step() seconds, and derived tokens_per_sec.  A healthy
    macro-stepping engine shows tokens >> dispatches; tokens ~= dispatches
    means the per-token path (FLAGS_decode_chunk=1) is active.  Also the
    prefix-cache hit/miss/avoided-token/eviction counters, the derived
    pool_bytes_per_resident capacity metric, and — for TP-sharded
    engines — pool_bytes_per_device (sharding-divided pool bytes) plus
    the mesh_shape string (docs/DECODE.md)."""
    out = dict(_DECODE_STATS)
    out["tokens_per_sec"] = (
        out["tokens"] / out["step_seconds"] if out["step_seconds"] else 0.0)
    out["pool_bytes_per_resident"] = (
        out["pool_bytes"] / out["resident_peak"] if out["resident_peak"]
        else 0.0)
    if reset:
        reset_decode_stats()
    return out


def reset_decode_stats():
    for k in _DECODE_STATS:
        if k == "parked_requests":
            # a GAUGE of live engine state (like the LoRA slot gauges):
            # a traffic-counter reset must not misreport the parking lot
            continue
        v = _DECODE_STATS[k]
        _DECODE_STATS[k] = "" if isinstance(v, str) else (
            0.0 if isinstance(v, float) else 0)


# Multi-tenant LoRA serving counters (profiler.lora_stats reads them):
# slots_resident = installed adapters on the most recent pack mutation;
# swaps = adapter installs into a slot (register_adapter, incl. LRU
# re-installs); evictions = slots vacated (explicit or LRU); gather
# dispatches = compiled decode dispatches that gathered per-row A/B from a
# pack; cache_epochs = slot-epoch bumps (each invalidates that slot's
# prefix-cache subtree); ship_ns_drops = shipped-page adoptions refused
# for a (slot, epoch) namespace mismatch (the pages were poured under
# adapter weights this engine no longer serves — dropping them loudly is
# the epoch-bump-strands-shipments contract, docs/SERVING_CLUSTER.md).
_LORA_STATS = {
    "slots_resident": 0,
    "slots_total": 0,
    "swaps": 0,
    "evictions": 0,
    "gather_dispatches": 0,
    "cache_epochs": 0,
    "ship_ns_drops": 0,
}


def lora_stats(reset: bool = False) -> dict:
    """Multi-tenant LoRA serving counters (docs/LORA.md): adapter slots
    resident / total on the most recent pack engine, hot swaps and
    evictions, decode dispatches that gathered adapter rows, prefix-cache
    epoch bumps, and shipped-page adoptions dropped for a namespace
    (slot, epoch) mismatch.  Zeros when no adapter engine ran."""
    out = dict(_LORA_STATS)
    if reset:
        reset_lora_stats()
    return out


def reset_lora_stats():
    # slots_resident/slots_total are GAUGES of live engine state, not
    # windowed traffic — a counter reset must not misreport the pack
    for k in _LORA_STATS:
        if k not in ("slots_resident", "slots_total"):
            _LORA_STATS[k] = 0


# Decode-chain schedule-search counters (schedule search, phase 2 —
# docs/SCHEDULE_SEARCH.md; profiler.schedule_search_stats merges these into
# the search-tier schema).  The SERVING module owns them because the engine
# is where decode-chain discovery/adoption happens: found = eligible
# engines that consulted the searcher for their macro-step geometry;
# accepted = engines whose compiled macro-step adopted a fused config;
# disabled = engines that kept the unfused ops (measured loss, cache
# verdict, a failed cache-config parity re-gate, or a mesh-lint
# violation on the sharded kernel); mesh_fused = the accepted subset
# whose engine is TP-sharded (the shard_map chain over the mesh);
# mesh_skipped = TP-sharded engines whose pools ride REPLICATED (head
# counts the mp axis doesn't divide) — no head-local layout to fuse
# over, a counted skip, never a crash.  prefill_chains_* mirror the same
# verdict schema for the chunked-prefill attention chain
# (PrefillChainSpec; single-device engines with prefill_chunk set).
_SCHED_DECODE_STATS = {
    "decode_chains_found": 0,
    "decode_chains_accepted": 0,
    "decode_chains_disabled": 0,
    "decode_chains_mesh_skipped": 0,
    "decode_chains_mesh_fused": 0,
    "prefill_chains_found": 0,
    "prefill_chains_accepted": 0,
    "prefill_chains_disabled": 0,
}


def schedule_decode_stats(reset: bool = False) -> dict:
    """Decode-chain counters for the schedule-search telemetry (see
    _SCHED_DECODE_STATS above; docs/SCHEDULE_SEARCH.md phase 2)."""
    out = dict(_SCHED_DECODE_STATS)
    if reset:
        reset_schedule_decode_stats()
    return out


def reset_schedule_decode_stats():
    for k in _SCHED_DECODE_STATS:
        _SCHED_DECODE_STATS[k] = 0


# Live engines hold compiled decode executables; any flag change may alter
# what those programs traced (FLAGS_decode_chunk, matmul precision, ...), so
# set_flags drops them — the same contract as the eager dispatch cache.
_ENGINES: "weakref.WeakSet[GenerationEngine]" = weakref.WeakSet()

# sentinel: the engine's decode-chain verdict is resolved lazily at the
# first _build_step and re-resolved after any flag change
_CHAIN_UNSET = object()


@_flags.on_change
def _invalidate_decode_steps(_changed):
    for eng in list(_ENGINES):
        eng._step_fns.clear()
        eng._draft_fn = eng._verify_fn = None
        # flags govern whether (and which) fused decode-chain schedule the
        # rebuilt steps may consume — re-resolve with the steps
        eng._decode_chain_cfg = _CHAIN_UNSET
        eng._prefill_chain_cfg = _CHAIN_UNSET


# SLO classes for add_request(priority=): admission order is (class, submit
# sequence) — FIFO within a class — and the deadline-pressure scheduler
# weights prefill-chunk grants by class (docs/DECODE.md admission scheduler)
_PRIORITY = {"high": 0, "normal": 1, "low": 2}
_PRI_NAMES = {v: k for k, v in _PRIORITY.items()}
# pressure = weight * (1 + boundaries waited): a request crossing
# _PRESSURE_ESCALATE doubles the macro-step's prefill-chunk budget, so
# HIGH escalates after 3 waited boundaries, NORMAL after 7, LOW after 15
_PRI_WEIGHT = {0: 4, 1: 2, 2: 1}
_PRESSURE_ESCALATE = 16


@dataclass
class _PrefillState:
    """Host bookkeeping for a PREFILLING slot (interleaved chunked
    prefill): pool pages and the slot are reserved at admission, then the
    prompt advances ONE pool block per granted chunk between decode
    dispatches — the chunk spans are fixed block-aligned offsets, never
    schedule-dependent, which is what keeps the stream bit-identical to
    atomic admission (the chunk boundary is pure data movement)."""
    req: dict                 # the queued submission (rid/prompt/nonce/...)
    caches: list              # naive per-layer K/V grown chunk-by-chunk
    matched: list             # shared prefix-cache pages (referenced)
    fresh: list               # exclusively owned pages (poured as we go)
    off: int = 0              # prompt tokens already forwarded
    poured: int = 0           # full blocks resident in the pool so far
    since: int = 0            # macro-step boundary when prefill began
    h: object = None          # last chunk's hidden states (first-token logits)


@dataclass
class _Slot:
    rid: object = None
    active: bool = False
    seq_len: int = 0          # tokens stored in the pool (incl. prompt)
    max_len: int = 0          # seq_len limit for this request
    blocks: list = field(default_factory=list)
    last_token: int = 0
    generated: list = field(default_factory=list)
    # per-request decode config (temperature-sampling tier; 0 = greedy)
    temperature: float = 0.0
    key: object = None        # precomputed PRNG key (seed + request nonce)
    d_seq_len: int = 0        # draft-pool coverage (speculative tier)
    adapter_slot: int = 0     # AdapterPack slot (0 = base-model identity)
    priority: int = 1        # SLO class (_PRIORITY; 2 = LOW = preemptible)
    req: object = None        # original submission (preemption re-queues it)
    prefill: object = None    # _PrefillState while PREFILLING, else None


class _PoolExhausted(RuntimeError):
    """Transient admission failure: not enough free (or reclaimable) pool
    blocks right now.  The engine queues the request for retry at the next
    macro-step boundary instead of surfacing this."""


class _RadixNode:
    __slots__ = ("chunk", "block", "children", "parent", "last_used")

    def __init__(self, chunk=None, block=-1, parent=None):
        self.chunk = chunk          # tuple of block_size token ids
        self.block = block          # pool block holding this chunk's K/V
        self.children = {}
        self.parent = parent
        self.last_used = 0


class RadixPrefixCache:
    """Host-side radix tree over token-id prefixes at PAGE granularity.

    Each node maps one FULL block's token chunk (a `block_size`-tuple of
    ids) to the pool block holding its K/V — for every layer at once, since
    a block id indexes all layers' pools at the same position.  `match`
    walks the prompt chunk-by-chunk and returns the longest cached run of
    blocks; `insert` adopts full prompt blocks freshly written by prefill.
    Reference-counting lives in the engine's allocator: the tree itself
    never pins a block, so a cached block with refcount zero is
    RECLAIMABLE, and `evict` frees such blocks leaf-first in LRU order
    (interior nodes only become evictable once their children are gone —
    a cached prefix is never torn out from under a longer cached one).
    Partial tail blocks are never inserted: the tail is re-prefilled
    per-request into an exclusively-owned page, which is the copy-on-write
    rule — shared pages are immutable, the mutable tail is always a
    private copy.

    Chunk keys are opaque: an adapter-aware engine namespaces the FIRST
    level with ``ns=(adapter_slot, slot_epoch)`` — root children key as
    ``(ns, chunk)`` — so tenants sharing a system prompt under the same
    adapter share pages while different adapters (whose K/V genuinely
    differ: adapted projections feed the cache) never cross-match, and a
    hot-swapped slot's bumped epoch strands exactly that slot's subtree
    (``drop_subtree`` reclaims it; docs/LORA.md).
    """

    def __init__(self, block_size):
        self.block_size = int(block_size)
        self._root = _RadixNode()
        self._by_block: dict[int, _RadixNode] = {}
        self._clock = 0

    def __len__(self):
        return len(self._by_block)

    def holds(self, block) -> bool:
        """Is this pool block owned by a tree node (i.e. cached)?"""
        return block in self._by_block

    def _tick(self):
        self._clock += 1
        return self._clock

    @staticmethod
    def _key(node_is_root, ns, chunk):
        return (ns, chunk) if (ns is not None and node_is_root) else chunk

    def match(self, tokens, max_blocks=None, ns=None):
        """Longest cached full-block prefix of `tokens` -> pool block list.

        Every matched node is LRU-touched.  `max_blocks` caps the walk
        (admission caps at (len-1)//block_size so at least one suffix
        token always prefills — the forward that produces the first
        logits).  `ns` namespaces the first chunk (adapter-aware engines
        pass (slot, epoch)); distinct namespaces never share nodes."""
        bs = self.block_size
        limit = len(tokens) // bs
        if max_blocks is not None:
            limit = min(limit, max_blocks)
        t = self._tick()
        node, out = self._root, []
        for bi in range(limit):
            chunk = tuple(tokens[bi * bs:(bi + 1) * bs])
            child = node.children.get(
                self._key(node is self._root, ns, chunk))
            if child is None:
                break
            child.last_used = t
            out.append(child.block)
            node = child
        return out

    def insert(self, tokens, blocks, ns=None):
        """Adopt `blocks[i]` as the cached page for tokens' i-th full
        chunk.  Existing nodes keep their block (first writer wins — the
        duplicate page stays request-private and recycles normally);
        returns the newly adopted blocks."""
        bs = self.block_size
        t = self._tick()
        node, adopted = self._root, []
        for bi in range(min(len(blocks), len(tokens) // bs)):
            chunk = tuple(tokens[bi * bs:(bi + 1) * bs])
            key = self._key(node is self._root, ns, chunk)
            child = node.children.get(key)
            if child is None:
                child = _RadixNode(key, blocks[bi], node)
                node.children[key] = child
                self._by_block[blocks[bi]] = child
                adopted.append(blocks[bi])
            child.last_used = t
            node = child
        return adopted

    def drop_subtree(self, ns, refcount):
        """Invalidate EXACTLY namespace `ns`'s subtree (a hot-swapped
        adapter slot): every node under first-level children keyed
        ``(ns, ...)`` leaves the tree.  Returns the refcount-zero blocks
        (immediately reclaimable — the caller frees them); blocks a live
        request still references merely stop being cached and recycle
        normally once that request drops them."""
        freed = []
        for key in [k for k in self._root.children
                    if isinstance(k, tuple) and len(k) == 2
                    and k[0] == ns]:
            stack = [self._root.children.pop(key)]
            while stack:
                nd = stack.pop()
                stack.extend(nd.children.values())
                del self._by_block[nd.block]
                if refcount[nd.block] == 0:
                    freed.append(nd.block)
        return freed

    def evict(self, n, refcount):
        """Free up to `n` RECLAIMABLE blocks: leaves whose refcount is
        zero, oldest-LRU first.  Refcounted blocks are untouchable — a
        request is still reading those pages.  Returns the freed blocks
        (the caller returns them to its free list).  One scan + a heap:
        an interior node enters the heap the moment its last child frees,
        so the whole reclaim is O(cached log cached), not O(n * cached)."""
        import heapq

        heap = [(nd.last_used, nd.block) for nd in self._by_block.values()
                if not nd.children and refcount[nd.block] == 0]
        heapq.heapify(heap)
        freed = []
        while heap and len(freed) < n:
            _, block = heapq.heappop(heap)
            victim = self._by_block[block]
            parent = victim.parent
            del parent.children[victim.chunk]
            del self._by_block[victim.block]
            freed.append(victim.block)
            if (parent is not self._root and not parent.children
                    and refcount[parent.block] == 0):
                heapq.heappush(heap, (parent.last_used, parent.block))
        return freed


class GenerationEngine:
    """Greedy continuous-batching decode over a shared paged-KV pool.

    Usage:
        eng = GenerationEngine(model, max_batch=4, block_size=16, num_blocks=64)
        eng.add_request("a", prompt_ids_a, max_new_tokens=8)
        while eng.has_work():
            for rid, toks in eng.step().items(): ...
        eng.result("a")  # -> list of generated token ids

    step() advances one MACRO-STEP of D = decode_chunk tokens per
    dispatch (D resolves to FLAGS_decode_chunk, default 8, when the
    constructor arg is None) and returns {rid: [tokens...]}; only at
    D == 1 — an explicit decode_chunk=1 or the flag set to 1 — does it
    return the legacy per-token {rid: token} shape.  Consumers that
    stream token-by-token should pass decode_chunk=1 or iterate the
    lists; `result(rid)` is unaffected either way (docs/DECODE.md).
    """

    def __init__(self, model, max_batch=4, block_size=16, num_blocks=128,
                 eos_token_id=None, mesh=None, mp_axis="mp",
                 prefill_chunk=None, draft_model=None,
                 num_speculative_tokens=4, decode_chunk=None,
                 prefix_cache=None, kv_cache_dtype=None, adapters=None,
                 prefill_chunk_blocks=None):
        """mesh: optional ProcessMesh/jax Mesh with an `mp_axis` dimension —
        the engine then serves TENSOR-PARALLEL: weights get Megatron
        placements (models.llama.shard_llama), the paged-KV pool is sharded
        over the KV-head dim, and the ONE compiled decode program runs
        GSPMD-partitioned over the mesh (VERDICT r3 #6; reference capability:
        analysis_predictor multi-device serving).  The WHOLE feature set
        composes with the mesh: int8 pools shard payload + quant scales
        leaf-wise on the same KV-head spec, adapter packs place their A/B
        factors on their base projections' Megatron split
        (nn.AdapterPack.place_over_mesh), speculative engines shard the
        draft model and its pools too, and token streams stay
        bit-identical to the single-device engine (docs/DECODE.md
        sharded-serving section).

        decode_chunk (None -> FLAGS_decode_chunk): macro-step width D —
        step() advances D tokens per compiled dispatch (a lax.scan over the
        single-token step with donated pools), admitting/retiring requests
        only at macro-step boundaries; rows that finish mid-chunk are
        masked onto their scratch page for the rest of the chunk (their
        K/V writes never touch the shared pool) and their surplus tokens
        are dropped on the host.  Token streams are bit-identical for
        every D.  step() returns {rid: token} when D == 1 (back-compat)
        and {rid: [tokens...]} when D > 1.  Ignored by speculative engines
        (their tick is already multi-token).

        prefix_cache (None -> FLAGS_prefix_cache): radix/prefix KV reuse —
        admission matches the longest cached token-id prefix at page
        granularity, takes REFERENCES to those pool pages instead of
        re-prefilling them, and prefills only the suffix; full prompt
        blocks written by prefill are inserted back into the tree, and
        refcount-zero leaves are evicted LRU under pool pressure.

        kv_cache_dtype (None -> FLAGS_kv_cache_dtype): 'bf16' keeps
        full-precision pools in the model's serving dtype (today's exact
        behavior); 'int8' stores quantized pools with per-block-per-head
        scales, dequantized on gather inside the jitted step — roughly
        double the resident requests at fixed pool bytes.

        adapters: multi-tenant LoRA serving (nn/lora.py, docs/LORA.md) —
        an int rank, a config dict ({"rank", "alpha", "max_adapters",
        "targets"}), or a prebuilt nn.AdapterPack.  Pre-allocates
        FLAGS_lora_max_adapters hot-swappable slots (plus reserved slot 0
        = the exact base-model identity); register_adapter/evict_adapter
        mutate slot CONTENTS only, at macro-step boundaries, so the
        compiled decode step — which gathers each batch row's A/B by its
        slot index — is reused across swaps with zero recompiles.
        Requests pick an adapter via add_request(..., adapter=name);
        mixed-adapter batches decode in ONE dispatch.  With draft_model=
        the DRAFT proposes with the base model (no per-tenant draft
        packs) while the target verifies through each row's adapter —
        emitted streams equal the plain adapter engine's; a
        heavily-shifted tenant just pays a lower acceptance rate.

        prefill_chunk_blocks (None -> FLAGS_prefill_chunk_blocks):
        INTERLEAVED chunked prefill — admission only reserves a slot and
        pool pages; the prompt then advances at most this many pool-block
        chunks per step() between decode dispatches (the PREFILLING
        state), so a long prompt never stalls resident streams' inter-
        token latency.  0 = atomic prefill at admission (legacy).
        Streams are bit-identical to atomic admission: every chunk is a
        fixed block-aligned span through the same cached forward, and
        the per-block pour writes the same bytes (and the same
        per-block quant scales) the atomic pour batches.  Ignored by
        speculative engines (their draft pour rides atomic admission)."""
        cfg = model.config
        self.model = model
        if prefill_chunk is not None and int(prefill_chunk) < 1:
            raise ValueError("prefill_chunk must be a positive token count")
        self.prefill_chunk = None if prefill_chunk is None else int(prefill_chunk)
        if prefill_chunk_blocks is not None and int(prefill_chunk_blocks) < 0:
            raise ValueError("prefill_chunk_blocks must be >= 0 "
                             "(0 = atomic prefill)")
        self.prefill_chunk_blocks = (None if prefill_chunk_blocks is None
                                     else int(prefill_chunk_blocks))
        self.block_size = int(block_size)
        self.max_batch = int(max_batch)
        self.eos_token_id = eos_token_id
        self._n_layers = cfg.num_hidden_layers
        self._nkv = cfg.num_key_value_heads
        self._head_dim = cfg.hidden_size // cfg.num_attention_heads

        self._pool_sharding = self._d_pool_sharding = None
        self._mp_axis = mp_axis
        if mesh is not None:
            from paddle_tpu.distributed.auto_parallel import ProcessMesh
            from paddle_tpu.models.llama import shard_llama

            if not isinstance(mesh, ProcessMesh):
                mesh = ProcessMesh(mesh)
            if mp_axis not in mesh.dim_names:
                raise ValueError(
                    f"mesh has no {mp_axis!r} axis: {mesh.dim_names}")
            shard_llama(model, mesh, mp_axis=mp_axis)
            # pool pages sharded over KV heads: each mp rank holds its
            # heads' pages; the paged-attention gather stays local
            self._pool_sharding = self._kv_pool_sharding(
                mesh, mp_axis, self._nkv, "")
        self.mesh = mesh

        from paddle_tpu.ops import paged_attention as pa

        # pool pages [num_blocks, Nkv, bs, H] per layer, plus one dedicated
        # scratch page per slot (masked lanes write there, never the pool)
        self._num_blocks = int(num_blocks)
        total = self._num_blocks + self.max_batch
        kv_dt = (kv_cache_dtype if kv_cache_dtype is not None
                 else _flags.flag("FLAGS_kv_cache_dtype"))
        if kv_dt not in ("bf16", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'bf16' or 'int8', got {kv_dt!r}")
        self._kv_dtype = kv_dt  # resolved ONCE: pools are allocated now
        dt = (jnp.int8 if kv_dt == "int8"
              else jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32)
        pools = [pa.alloc_paged_cache(total, self._nkv, self.block_size,
                                      self._head_dim, dt)
                 for _ in range(self._n_layers)]
        # leaf-wise placement: a QuantPool's int8 payload [blocks,Nkv,bs,H]
        # and its f32 scales [blocks,Nkv] both shard on the KV-head dim
        # (the same PartitionSpec(None, mp) covers both ranks — trailing
        # dims replicate), so int8 pools compose with the mesh engine
        self._kpools = [self._place_pool(k, self._pool_sharding)
                        for k, _ in pools]
        self._vpools = [self._place_pool(v, self._pool_sharding)
                        for _, v in pools]
        self._free = list(range(self._num_blocks))
        self._ref = [0] * total  # per-block request refcounts (allocator)
        pc = (bool(prefix_cache) if prefix_cache is not None
              else bool(_flags.flag("FLAGS_prefix_cache")))
        self._prefix = RadixPrefixCache(self.block_size) if pc else None
        self._pending: deque = deque()  # admission retries (pool pressure)
        self._parked: dict = {}   # rid -> parked record (preempted LOWs)
        self._submit_seq = 0      # admission tie-break within an SLO class
        self._scratch = [self._num_blocks + i for i in range(self.max_batch)]
        self._slots = [_Slot() for _ in range(self.max_batch)]
        self._results: dict = {}
        self._max_blocks_per_seq = max(2, self._num_blocks // max(1, self.max_batch))
        if decode_chunk is not None and int(decode_chunk) < 1:
            raise ValueError("decode_chunk must be >= 1")
        self._decode_chunk = None if decode_chunk is None else int(decode_chunk)
        self._step_fns: dict = {}  # macro-step executables, keyed by D
        self._decode_chain_cfg = _CHAIN_UNSET  # lazy (_resolve_decode_chain)
        self._prefill_chain_cfg = _CHAIN_UNSET  # lazy (_resolve_prefill_chain)
        # masked lanes' block tables (every page is the slot's scratch
        # page): constant, so committed to the device ONCE here — not
        # re-transferred on every dispatch
        self._scratch_tables = jnp.asarray(np.tile(
            np.asarray(self._scratch, np.int32)[:, None],
            (1, self._max_blocks_per_seq)))
        self._req_counter = 0
        self._state = list(model.state_dict().values())
        # ---- fault-tolerance tier (serving/snapshot.py) -----------------
        self._macro_steps = 0          # boundary count; snapshot step tags
        self._last_auto_snapshot = 0   # boundary of the last periodic save
        self._snapshot_store = None    # cached EngineSnapshot (valid-cache)
        self._draining = False         # drain(): admissions closed
        self._drain_step = None        # committed handoff step (idempotence)
        self._drain_dir = None         # ...and where it committed
        self._preempt_requested = False
        self._preempt_saved = False
        self._prev_handlers: dict = {}
        _ENGINES.add(self)

        # ---- speculative tier: draft model + its own paged pools --------
        self.draft_model = draft_model
        self.num_speculative = int(num_speculative_tokens)
        self._draft_fn = self._verify_fn = None
        if draft_model is not None:
            if self.num_speculative < 1:
                raise ValueError("num_speculative_tokens must be >= 1")
            dc = draft_model.config
            if dc.vocab_size != cfg.vocab_size:
                raise ValueError("draft and target must share a vocabulary")
            if mesh is not None and draft_model is not model:
                # the draft serves the same mesh: Megatron placements on
                # its weights, its pools sharded over ITS KV-head count
                # (which may differ from the target's)
                from paddle_tpu.models.llama import shard_llama

                shard_llama(draft_model, mesh, mp_axis=mp_axis)
            self._d_layers = dc.num_hidden_layers
            self._d_nkv = dc.num_key_value_heads
            self._d_hd = dc.hidden_size // dc.num_attention_heads
            if mesh is not None:
                self._d_pool_sharding = self._kv_pool_sharding(
                    mesh, mp_axis, self._d_nkv, "draft ")
            ddt = (jnp.int8 if kv_dt == "int8"
                   else jnp.bfloat16 if dc.dtype == "bfloat16" else jnp.float32)
            d_pools = [pa.alloc_paged_cache(total, self._d_nkv,
                                            self.block_size, self._d_hd, ddt)
                       for _ in range(self._d_layers)]
            self._d_kpools = [self._place_pool(k, self._d_pool_sharding)
                              for k, _ in d_pools]
            self._d_vpools = [self._place_pool(v, self._d_pool_sharding)
                              for _, v in d_pools]
            self._d_state = list(draft_model.state_dict().values())
            self._spec_stats = {"ticks": 0, "proposed": 0, "accepted": 0,
                                "emitted": 0}

        # ---- multi-tenant LoRA tier: slot-stacked adapter pack ----------
        self._pack = None
        if adapters is not None:
            from paddle_tpu.nn.lora import AdapterPack

            # speculative + adapters composes with a BASE-MODEL draft:
            # the draft proposes adapter-free tokens and the target
            # verifies through each row's adapter, so the emitted stream
            # is exactly the plain adapter engine's (greedy acceptance
            # only ever keeps tokens the adapted target would decode) —
            # a heavily-shifted tenant just pays a lower acceptance rate
            if isinstance(adapters, AdapterPack):
                self._pack = adapters
            elif isinstance(adapters, int):
                self._pack = AdapterPack(model, rank=adapters)
            elif isinstance(adapters, dict):
                self._pack = AdapterPack(model, **adapters)
            else:
                raise TypeError(
                    "adapters must be an int rank, a config dict, or an "
                    f"nn.AdapterPack; got {type(adapters).__name__}")
            if mesh is not None:
                # A/B factors ride the base projections' Megatron split
                # (col targets shard B's out dim, row targets shard A's
                # in dim); recorded shardings are re-applied after every
                # slot scatter so hot swaps keep one compiled signature
                self._pack.place_over_mesh(mesh.jax_mesh, mp_axis=mp_axis)
            S = self._pack.num_slots
            self._adapter_registry: dict = {}   # name -> (arrays, alpha)
            self._slot_names = [None] * S       # slot -> installed name
            self._slot_epochs = [0] * S         # bumped per content change
            self._slot_refs = [0] * S           # in-flight request counts
            self._slot_used = [0] * S           # LRU clock marks
            self._slot_clock = 0
            _LORA_STATS["slots_total"] = S - 1
            _LORA_STATS["slots_resident"] = 0
        all_pools = (self._kpools + self._vpools
                     + getattr(self, "_d_kpools", [])
                     + getattr(self, "_d_vpools", []))
        _DECODE_STATS["pool_bytes"] = sum(pa.pool_nbytes(p)
                                          for p in all_pools)
        # per-device footprint: each pool leaf's committed sharding
        # divides its bytes (== pool_bytes on single-device engines)
        _DECODE_STATS["pool_bytes_per_device"] = sum(
            pa.pool_device_nbytes(p) for p in all_pools)
        _DECODE_STATS["mesh_shape"] = "" if mesh is None else "x".join(
            f"{n}{s}" for n, s in zip(mesh.dim_names, mesh.shape))
        if _flags.flag("FLAGS_verify_sharding"):
            # mesh lint at construction: param/pool placements, pool
            # donation aliasing, per-device HBM estimate — abstract, so a
            # replicated-pool blowup or a double-donated pool buffer fails
            # loudly here, before the first decode dispatch
            from paddle_tpu.static.mesh_lint import lint_engine

            lint_engine(self, raise_on_error=True)

    # ------------------------------------------------------ pool placement
    @staticmethod
    def _kv_pool_sharding(mesh, mp_axis, nkv, who):
        """NamedSharding for a paged pool on the TP mesh: pages shard
        over the KV-head dim (axis 1) when the axis divides the head
        count; otherwise replicated with a warning.  The SAME spec covers
        a QuantPool's rank-2 scales [blocks, Nkv] — trailing dims
        replicate — so int8 pools place leaf-wise through it."""
        from jax.sharding import NamedSharding, PartitionSpec

        mp = mesh.get_dim_size(mp_axis)
        if nkv % mp == 0:
            return NamedSharding(mesh.jax_mesh,
                                 PartitionSpec(None, mp_axis))
        import warnings

        warnings.warn(
            f"num_key_value_heads={nkv} not divisible by mp={mp}; "
            f"{who}KV pool replicated", stacklevel=3)
        return NamedSharding(mesh.jax_mesh, PartitionSpec())

    @staticmethod
    def _place_pool(pool, sharding):
        """Commit a pool (plain array or QuantPool pytree) to `sharding`
        leaf-wise; identity when sharding is None (single device)."""
        if sharding is None:
            return pool
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), pool)

    # ------------------------------------------------------------ requests
    def has_work(self):
        # a DRAINING engine's queued requests are not its work: they rode
        # the drain snapshot and belong to the restore target (serving
        # them here too would double-serve; counting them here would make
        # the lame-duck `while has_work(): step()` loop spin forever).
        # PREFILLING slots and the parked lot count: both finish through
        # future boundaries (drain() demotes them to the queue first).
        return any(s.active or s.prefill is not None
                   for s in self._slots) or (
            (bool(self._pending) or bool(self._parked))
            and not self._draining)

    def pending_requests(self):
        """Request ids queued for admission (pool pressure); they retry at
        the next macro-step boundary."""
        return [req["rid"] for req in self._pending]

    def parked_requests(self):
        """Request ids preempted into the host-side parking lot (their
        pool pages live host-side; they re-admit bit-identically at a
        later boundary — docs/DECODE.md preemption)."""
        return list(self._parked)

    def prefilling_requests(self):
        """Request ids in the PREFILLING state (interleaved chunked
        prefill in progress; docs/DECODE.md admission scheduler)."""
        return [s.rid for s in self._slots if s.prefill is not None]

    def result(self, rid):
        return self._results.get(rid)

    # ---------------------------------------------------- adapter registry
    def _require_pack(self):
        if self._pack is None:
            raise RuntimeError(
                "this engine was built without adapters=; pass "
                "GenerationEngine(adapters=rank_or_config) to serve "
                "multi-tenant LoRA (docs/LORA.md)")
        return self._pack

    def _slot_of(self, name):
        return next((s for s, n in enumerate(self._slot_names) if n == name),
                    None)

    def _touch_slot(self, slot):
        self._slot_clock += 1
        self._slot_used[slot] = self._slot_clock

    def _bump_epoch(self, slot):
        """Invalidate exactly `slot`'s prefix-cache subtree: the old
        (slot, epoch) namespace becomes unreachable and its refcount-zero
        pages return to the free list NOW."""
        if self._prefix is not None:
            freed = self._prefix.drop_subtree(
                (slot, self._slot_epochs[slot]), self._ref)
            self._free.extend(freed)
        self._slot_epochs[slot] += 1
        _LORA_STATS["cache_epochs"] += 1

    def _resident_count(self):
        return sum(1 for n in self._slot_names[1:] if n is not None)

    def register_adapter(self, name, state_dict, alpha=None):
        """Register a LoRA adapter (an adapter-only state dict — see
        nn.lora.lora_state_dict) and install it into a pack slot if one is
        free or LRU-reclaimable.  Returns the slot index, or None when
        every slot currently serves in-flight requests — the adapter stays
        registered and installs lazily when one of its requests is
        admitted at a macro-step boundary (requests never raise on slot
        exhaustion; they QUEUE, same FIFO contract as pool exhaustion).

        Installation is a pure device scatter into pre-allocated arrays:
        pack geometry (rank, slot count, targets) never changes, so the
        compiled decode step is reused — zero recompiles per swap.
        `alpha` defaults to the pack's alpha (scaling = alpha/rank is
        per-slot, so tenants may differ).

        Re-registering a RESIDENT name updates its slot in place (new
        weights scattered, epoch bumped so stale cached prefixes die) —
        refused while the adapter has in-flight ACTIVE requests, whose
        streams must not change weights mid-flight (queued requests are
        fine: they haven't started and will serve the new version)."""
        pack = self._require_pack()
        from paddle_tpu.nn.lora import parse_adapter_state_dict

        arrays = parse_adapter_state_dict(
            state_dict, pack.num_layers, pack.targets, pack.rank)
        slot = self._slot_of(name)
        if slot is not None:
            if self._slot_refs[slot] > 0:
                raise RuntimeError(
                    f"adapter {name!r} has in-flight requests; "
                    "re-registering would change their weights "
                    "mid-stream — drain them first")
            self._adapter_registry[name] = (arrays, alpha)
            self._bump_epoch(slot)
            self._pack.set_slot(slot, arrays, alpha)
            self._touch_slot(slot)
            _LORA_STATS["swaps"] += 1
            return slot
        self._adapter_registry[name] = (arrays, alpha)
        return self._try_install(name)

    def _try_install(self, name):
        """Make `name` resident: reuse its slot, take a free one, or evict
        the LRU idle slot (never one with in-flight requests).  Returns
        the slot index or None (transient exhaustion — every slot busy)."""
        slot = self._slot_of(name)
        if slot is not None:
            self._touch_slot(slot)
            return slot
        arrays, alpha = self._adapter_registry[name]
        S = self._pack.num_slots
        free = next((s for s in range(1, S) if self._slot_names[s] is None),
                    None)
        if free is None:
            idle = [s for s in range(1, S) if self._slot_refs[s] == 0]
            if not idle:
                return None
            free = min(idle, key=lambda s: self._slot_used[s])
            self._slot_names[free] = None
            _LORA_STATS["evictions"] += 1
        # the install overwrites EVERY target (omitted ones zero), so no
        # separate clear; the epoch bump strands the old contents' cached
        # prefix subtree before the new tenant can be matched against it
        self._bump_epoch(free)
        self._pack.set_slot(free, arrays, alpha)
        self._slot_names[free] = name
        self._touch_slot(free)
        _LORA_STATS["swaps"] += 1
        _LORA_STATS["slots_resident"] = self._resident_count()
        return free

    def evict_adapter(self, name):
        """Unregister `name` and vacate its slot.  REFUSES (raises) while
        the adapter has in-flight requests — active slots or queued
        admissions; retire or drain them first.  The slot's prefix-cache
        subtree is invalidated and its contents zeroed."""
        self._require_pack()
        if name not in self._adapter_registry:
            raise KeyError(f"adapter {name!r} is not registered")
        slot = self._slot_of(name)
        in_flight = (slot is not None and self._slot_refs[slot] > 0)
        if in_flight or any(r.get("adapter") == name for r in self._pending):
            raise RuntimeError(
                f"adapter {name!r} has in-flight requests "
                f"({'active' if in_flight else 'queued'}); drain them "
                "before evicting")
        del self._adapter_registry[name]
        if slot is not None:
            self._slot_names[slot] = None
            self._bump_epoch(slot)
            self._pack.clear_slot(slot)
            _LORA_STATS["evictions"] += 1
            _LORA_STATS["slots_resident"] = self._resident_count()

    def adapter_slots(self):
        """{adapter name: slot index} for currently RESIDENT adapters
        (registered-but-swapped-out adapters are absent)."""
        self._require_pack()
        return {n: s for s, n in enumerate(self._slot_names)
                if n is not None}

    def _alloc(self, n):
        """Pop n blocks (refcount 1 each).  Under pressure, reclaimable
        prefix-cache pages (refcount-zero LRU leaves) are evicted first;
        a genuine shortfall raises _PoolExhausted — admission backs out
        and queues, it never surfaces to the caller mid-submit."""
        if len(self._free) < n and self._prefix is not None:
            freed = self._prefix.evict(n - len(self._free), self._ref)
            self._free.extend(freed)
            _DECODE_STATS["prefix_evictions"] += len(freed)
        if len(self._free) < n:
            raise _PoolExhausted(
                f"paged pool exhausted: need {n} blocks, {len(self._free)} free"
            )
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def _unref(self, blocks):
        """Drop one reference per block; blocks reaching refcount zero
        return to the free list UNLESS the prefix tree caches them — those
        stay resident as reclaimable pages until LRU eviction."""
        for b in blocks:
            self._ref[b] -= 1
            if self._ref[b] <= 0 and (
                    self._prefix is None or not self._prefix.holds(b)):
                self._free.append(b)

    def _release(self, slot):
        self._unref(slot.blocks)
        if self._pack is not None:
            self._slot_refs[slot.adapter_slot] -= 1
            slot.adapter_slot = 0
        slot.blocks = []
        slot.active = False
        slot.rid = None
        slot.req = None
        slot.prefill = None

    def add_request(self, rid, prompt_ids, max_new_tokens=16,
                    temperature=None, seed=0, adapter=None, nonce=None,
                    priority="normal"):
        """Prefill the prompt, pour K/V into pool pages, occupy a slot.

        With the prefix cache on, the longest cached token-id prefix is
        matched at page granularity first: those pages are REFERENCED (not
        re-prefilled) and only the suffix runs through the model.

        Under pool pressure (or with no free slot) the request is QUEUED
        instead of raising: admission retries at the next macro-step
        boundary, and `add_request` returns None (the first generated
        token otherwise).  Its PRNG nonce is reserved at submit time, so a
        queued-then-admitted sampled request draws the same stream an
        immediately-admitted one would.  Requests that can NEVER fit
        (wider than the per-seq block table) still raise.

        temperature: None/0 -> greedy decode for this request;
        > 0 -> per-request temperature sampling, deterministic per
        (seed, join order) — the seed is folded with a per-request nonce so
        same-seed requests still draw distinct streams, and each request
        folds its OWN generated-token counter per step.  Requests with
        different decode configs share the ONE compiled decode program
        (the config rides in as per-slot arrays).

        adapter: name of a REGISTERED LoRA adapter (register_adapter) to
        serve this request with; None = the base model (pack slot 0).
        A request whose adapter cannot be made resident right now (every
        slot busy with in-flight requests) QUEUES exactly like pool
        exhaustion — FIFO retry at the next macro-step boundary, with the
        PRNG nonce reserved at submit so a queued-then-admitted stream
        matches immediate admission bit-for-bit.  An UNREGISTERED adapter
        name raises KeyError (nothing to wait for).

        nonce: EXPLICIT submit-time nonce (serving/cluster.py's router
        assigns these globally) instead of this engine's local counter —
        a request re-dispatched to a DIFFERENT replica after a crash
        draws exactly the stream the dead replica would have, because the
        sampling key is (seed, nonce) and both are now request identity,
        not engine state.  The local counter advances past any explicit
        nonce so mixed use can never collide.

        priority: SLO class — "high" | "normal" | "low".  Admission at
        macro-step boundaries runs in (class, submit order) — FIFO
        within a class — the deadline-pressure scheduler weights
        interleaved prefill-chunk grants by class, and LOW requests are
        PREEMPTIBLE (FLAGS_preempt_low_priority): when a higher class
        cannot be admitted, a LOW resident's pages park host-side and
        its stream resumes bit-identically on re-admission (submit-time
        nonces make the stream request identity, not engine state).

        With interleaved chunked prefill active (prefill_chunk_blocks /
        FLAGS_prefill_chunk_blocks > 0) add_request ALWAYS returns None:
        prefill spreads over future step() boundaries, and the first
        token surfaces through step()'s output as a queued admission
        would (a list-valued entry led by token #1)."""
        if self._draining:
            raise RuntimeError(
                "engine is draining (drain(): migration snapshot taken, "
                "admissions closed) — submit to the restored engine "
                "instead (docs/CHECKPOINT.md serving section)")
        if self.draft_model is not None and float(temperature or 0.0) > 0.0:
            # checked BEFORE any allocation/prefill: a rejected request
            # must not leak pool blocks or burn two prefills
            raise ValueError(
                "speculative decoding slots are greedy-only (sampled "
                "acceptance needs rejection sampling); drop temperature")
        prompt = np.asarray(prompt_ids, np.int32).reshape(1, -1)
        max_len = prompt.shape[1] + int(max_new_tokens)
        # speculative verify overshoots by up to K+1 positions past the
        # budget before lens bookkeeping rolls back — those writes must
        # land in pages the request OWNS, never in the table-padding block
        headroom = 0 if self.draft_model is None else self.num_speculative + 1
        n_blocks = -(-(max_len + headroom) // self.block_size)
        if n_blocks > self._max_blocks_per_seq:
            raise RuntimeError(
                f"request needs {n_blocks} blocks > per-seq table width "
                f"{self._max_blocks_per_seq}"
            )
        if adapter is not None:
            self._require_pack()
            if adapter not in self._adapter_registry:
                raise KeyError(
                    f"adapter {adapter!r} is not registered on this "
                    "engine; call register_adapter first")
        if priority not in _PRIORITY:
            raise ValueError(
                f"priority must be one of {sorted(_PRIORITY)}, "
                f"got {priority!r}")
        # nonce reserved at SUBMIT time: retry timing can't shift the
        # request's sampling stream
        if nonce is None:
            nonce = self._req_counter
            self._req_counter += 1
        else:
            nonce = int(nonce)
            self._req_counter = max(self._req_counter, nonce + 1)
        req = {"rid": rid, "prompt": prompt, "max_len": max_len,
               "n_blocks": n_blocks,
               "temperature": float(temperature or 0.0),
               "seed": int(seed), "nonce": nonce, "adapter": adapter,
               "pri": _PRIORITY[priority], "seq": self._submit_seq}
        self._submit_seq += 1
        if self._prefill_chunk_blocks() > 0:
            # interleaved mode: admission happens at boundaries only (the
            # chunk scheduler owns the prefill work); first tokens surface
            # through step() exactly like queued admissions
            self._pending.append(req)
            return None
        # fairness: while older same-or-higher-class requests wait,
        # newcomers queue behind (the boundary scheduler orders the queue
        # by (class, submit order); all-default-priority traffic is FIFO —
        # the original contract)
        if self._pending or not self._try_admit(req):
            self._pending.append(req)
            return None
        return self._results[rid][0]

    def _prefill_chunk_blocks(self) -> int:
        """Per-macro-step prefill budget N in pool blocks (0 = atomic
        prefill at admission).  Speculative engines always resolve 0:
        their admission pours the draft pools too, and interleaving
        would desynchronize d_seq_len mid-prefill."""
        if self.draft_model is not None:
            return 0
        if self.prefill_chunk_blocks is not None:
            return self.prefill_chunk_blocks
        return max(0, int(_flags.flag("FLAGS_prefill_chunk_blocks")))

    def _admit_pending(self):
        """Retry queued admissions — called at macro-step boundaries — in
        (priority class, submit order): parked (preempted) requests
        compete in the SAME ordering as queued ones.  When the head
        candidate is above LOW and cannot be admitted, a LOW resident may
        be preempted to make room (FLAGS_preempt_low_priority).  Returns
        the admitted request ids whose FIRST token is already available
        (atomic admissions): it surfaces through this step()'s output.
        Interleaved reservations enter the PREFILLING state instead —
        their rids surface later, when _advance_prefills finishes them —
        and re-admitted parked requests already delivered token #1, so
        neither appears in the returned list."""
        admitted = []
        interleaved = self._prefill_chunk_blocks() > 0
        while True:
            cands = [((rec["req"].get("pri", 2), rec["req"].get("seq", 0)),
                      None, rid) for rid, rec in self._parked.items()]
            cands += [((req.get("pri", 1), req.get("seq", 0)), req,
                       req["rid"]) for req in self._pending]
            if not cands:
                break
            _key, req, rid = min(cands, key=lambda c: c[0])
            if req is None:
                ok = self._try_unpark(rid)
            elif interleaved:
                ok = self._begin_prefill(req)
                if ok:
                    self._drop_pending(req)
            else:
                ok = self._try_admit(req)
                if ok:
                    self._drop_pending(req)
                    admitted.append(rid)
            if ok:
                continue
            # head-of-line blocked: a request above LOW may evict a LOW
            # resident (its pages park host-side) and retry
            if _key[0] < _PRIORITY["low"] and self._preempt_one():
                continue
            if not any(s.active or s.prefill is not None
                       for s in self._slots):
                # nothing resident to drain and still no room: the
                # engine can never make progress — be loud
                raise RuntimeError(
                    f"queued request {rid!r} cannot be admitted "
                    "with an idle engine (pool too small?)")
            break
        return admitted

    def _drop_pending(self, req):
        # remove by IDENTITY: req dicts hold numpy prompts, so deque's
        # ==-based remove could raise on a truth-ambiguous array compare
        for i, r in enumerate(self._pending):
            if r is req:
                del self._pending[i]
                return

    def _try_admit(self, req):
        """One admission attempt: prefix-match, allocate, prefill the
        suffix, pour, occupy a slot.  Returns False (with ALL state backed
        out — no leaked blocks, no occupied slot, no stolen references) on
        transient shortage; real errors back out and re-raise."""
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import _model_forward_cached

        slot = next((s for s in self._slots if not s.active), None)
        if slot is None:
            return False
        # ---- adapter residency: the request's adapter must hold a pack
        # slot before prefill (adapted projections feed the K/V it pours).
        # Transient slot exhaustion — every slot serving in-flight
        # requests — queues exactly like pool exhaustion.
        ad_slot = 0
        if self._pack is not None and req.get("adapter") is not None:
            ad_slot = self._try_install(req["adapter"])
            if ad_slot is None:
                return False
        prompt = req["prompt"]
        s0 = prompt.shape[1]
        bs = self.block_size
        # ---- prefix match: reference cached pages instead of prefilling.
        # Capped at (s0-1)//bs full blocks so at least one suffix token
        # always prefills — that forward produces the first-token logits.
        # Adapter engines namespace the walk by (slot, epoch): tenants
        # sharing a prompt under one adapter share pages, other adapters
        # (different K/V!) never cross-match, and a swapped slot's bumped
        # epoch makes its old subtree unmatchable.
        ns = ((ad_slot, self._slot_epochs[ad_slot])
              if self._pack is not None else None)
        toks = matched = None
        if self._prefix is not None:
            # token list cached across retries (the prompt is immutable);
            # the match itself re-walks each attempt on purpose — the
            # LRU touch keeps a waiting request's pages warm for its
            # retry instead of letting pressure evict them
            toks = req.setdefault("toks", [int(t) for t in prompt[0]])
            matched = self._prefix.match(toks, max_blocks=(s0 - 1) // bs,
                                         ns=ns)
            for b in matched:
                self._ref[b] += 1
        matched = matched or []
        try:
            fresh = self._alloc(req["n_blocks"] - len(matched))
        except _PoolExhausted:
            self._unref(matched)
            return False
        blocks = matched + fresh
        m_len = len(matched) * bs

        model = self.model
        try:
            caches = self._prefix_or_empty(
                self._kpools, self._vpools, matched, m_len, self._n_layers,
                self._nkv, self._head_dim, model.config.dtype)
            # adapter requests prefill THROUGH their adapter: forward-post
            # hooks add each target projection's (x A)(B) s delta, so the
            # poured K/V matches what the adapted model would cache
            # (slot 0 installs no hooks — exact base-model prefill)
            if self._pack is not None and ad_slot:
                from paddle_tpu.nn.lora import adapter_prefill_scope

                prefill_ctx = adapter_prefill_scope(
                    model.model.layers, self._pack, ad_slot)
            else:
                prefill_ctx = contextlib.nullcontext()
            with prefill_ctx, paddle.no_grad():
                if (self.prefill_chunk is None
                        or s0 - m_len <= self.prefill_chunk):
                    h, caches = _model_forward_cached(
                        model.model, paddle.to_tensor(prompt[:, m_len:]),
                        caches, m_len)
                else:
                    # chunked prefill: fixed-size chunks through the cached
                    # forward (bottom-right-aligned cross-length attention)
                    # cap the peak activation footprint for long prompts.
                    # An accepted prefill-chain config routes each
                    # DIVISIBLE chunk's attention core through the fused
                    # K-tiled kernel (schedule search; PrefillChainSpec)
                    from paddle_tpu.models.llama import prefill_chain_scope

                    pf_cfg = self._resolve_prefill_chain()
                    with prefill_chain_scope(pf_cfg):
                        off = m_len
                        while off < s0:
                            chunk = prompt[:, off:off + self.prefill_chunk]
                            h, caches = _model_forward_cached(
                                model.model, paddle.to_tensor(chunk),
                                caches, off)
                            off += chunk.shape[1]
                logits_last = model._logits(h[:, -1:, :])._value[0, -1, :]
                first = int(np.asarray(jnp.argmax(logits_last)))

            # pour the suffix K/V into this request's exclusive pages
            # (matched prefix pages are shared and immutable)
            self._pour(self._kpools, self._vpools, caches, blocks, s0,
                       self._nkv, self._head_dim,
                       sharding=self._pool_sharding, start_tok=m_len)
            if self.draft_model is not None:
                # draft prefill over the same suffix into the draft pools
                # (cached pages were poured to BOTH pool sets at insert
                # time, so a matched prefix covers the draft too)
                d_caches = self._prefix_or_empty(
                    self._d_kpools, self._d_vpools, matched, m_len,
                    self._d_layers, self._d_nkv, self._d_hd,
                    self.draft_model.config.dtype)
                with paddle.no_grad():
                    _, d_caches = _model_forward_cached(
                        self.draft_model.model,
                        paddle.to_tensor(prompt[:, m_len:]), d_caches, m_len)
                self._pour(self._d_kpools, self._d_vpools, d_caches, blocks,
                           s0, self._d_nkv, self._d_hd,
                           sharding=self._d_pool_sharding, start_tok=m_len)
                slot.d_seq_len = s0
        except BaseException:
            # back out cleanly: pour only ever wrote the fresh pages, so
            # returning them (and the prefix references) restores the
            # allocator exactly
            for b in fresh:
                self._ref[b] = 0
                self._free.append(b)
            self._unref(matched)
            raise

        slot.rid = req["rid"]
        slot.active = True
        slot.seq_len = s0
        slot.max_len = req["max_len"]
        slot.blocks = blocks
        slot.adapter_slot = ad_slot
        slot.priority = req.get("pri", _PRIORITY["normal"])
        slot.req = req
        slot.prefill = None
        if self._pack is not None:
            # in-flight reference pins the adapter slot: LRU install and
            # evict_adapter both refuse referenced slots
            self._slot_refs[ad_slot] += 1
            self._touch_slot(ad_slot)
        slot.temperature = req["temperature"]
        # seed folded with the submit-time nonce: same-seed requests get
        # distinct streams and retries reproduce them
        slot.key = np.asarray(
            jax.random.fold_in(jax.random.PRNGKey(req["seed"]),
                               req["nonce"]))
        if slot.temperature > 0.0:
            # re-pick the FIRST token by sampling (prefill used argmax);
            # fold index 0 = this request's first generated token
            lg = logits_last.astype(jnp.float32) / slot.temperature
            key = jax.random.fold_in(jnp.asarray(slot.key), 0)
            first = int(np.asarray(jax.random.categorical(key, lg)))
        slot.last_token = first
        slot.generated = [first]
        self._results[slot.rid] = slot.generated
        if self._prefix is not None:
            # full prompt blocks become shared pages for future requests
            # (matched nodes just get LRU-touched); the partial tail block
            # stays request-private — the copy-on-write rule
            self._prefix.insert(toks, blocks[:s0 // bs], ns=ns)
            # hit/miss telemetry counts COMMITTED admissions only: a
            # queued-then-retried or prefill-errored attempt must not
            # inflate the avoided-prefill tokens
            if matched:
                _DECODE_STATS["prefix_hits"] += 1
                _DECODE_STATS["prefix_hit_tokens"] += m_len
            else:
                _DECODE_STATS["prefix_misses"] += 1
        _DECODE_STATS["resident_peak"] = max(
            _DECODE_STATS["resident_peak"],
            sum(1 for s in self._slots if s.active))
        _DECODE_STATS["admitted_" + _PRI_NAMES[slot.priority]] += 1
        if self.eos_token_id is not None and first == self.eos_token_id:
            self._finish(slot)
        elif slot.seq_len + 1 >= slot.max_len:
            self._finish(slot)
        return True

    # ------------------------------------- interleaved prefill (PREFILLING)
    def _begin_prefill(self, req):
        """Interleaved admission, reservation half: claim a slot, adapter
        residency, prefix-cache pages, and fresh pool blocks NOW — then
        hand the prompt to the chunk scheduler.  The slot enters the
        PREFILLING state (`slot.prefill` set, `active` False: the decode
        dispatch masks the lane onto its scratch page exactly like an
        empty slot) and _advance_prefills forwards it one pool block per
        granted chunk.  Returns False — fully backed out, same contract
        as _try_admit — on transient shortage."""
        slot = next((s for s in self._slots
                     if not s.active and s.prefill is None), None)
        if slot is None:
            return False
        ad_slot = 0
        if self._pack is not None and req.get("adapter") is not None:
            ad_slot = self._try_install(req["adapter"])
            if ad_slot is None:
                return False
        prompt = req["prompt"]
        s0 = prompt.shape[1]
        bs = self.block_size
        ns = ((ad_slot, self._slot_epochs[ad_slot])
              if self._pack is not None else None)
        matched = None
        if self._prefix is not None:
            toks = req.setdefault("toks", [int(t) for t in prompt[0]])
            matched = self._prefix.match(toks, max_blocks=(s0 - 1) // bs,
                                         ns=ns)
            for b in matched:
                self._ref[b] += 1
        matched = matched or []
        try:
            fresh = self._alloc(req["n_blocks"] - len(matched))
        except _PoolExhausted:
            self._unref(matched)
            return False
        m_len = len(matched) * bs
        try:
            caches = self._prefix_or_empty(
                self._kpools, self._vpools, matched, m_len, self._n_layers,
                self._nkv, self._head_dim, self.model.config.dtype)
        except BaseException:
            for b in fresh:
                self._ref[b] = 0
                self._free.append(b)
            self._unref(matched)
            raise
        slot.rid = req["rid"]
        slot.blocks = matched + fresh
        slot.adapter_slot = ad_slot
        slot.priority = req.get("pri", _PRIORITY["normal"])
        slot.req = req
        if self._pack is not None:
            self._slot_refs[ad_slot] += 1
            self._touch_slot(ad_slot)
        slot.prefill = _PrefillState(
            req=req, caches=caches, matched=list(matched),
            fresh=list(fresh), off=m_len, poured=len(matched),
            since=self._macro_steps)
        return True

    def _pressure(self, slot) -> int:
        """Deadline pressure of a PREFILLING slot: class weight scaled by
        boundaries waited.  Deterministic in macro-steps — the budget
        math never consults wall clocks, so schedules (and therefore
        token streams) reproduce run-to-run."""
        st = slot.prefill
        waited = self._macro_steps - st.since
        return _PRI_WEIGHT[slot.priority] * (1 + waited)

    def _prefill_budget(self) -> int:
        """Prefill-chunk grants for THIS macro-step.  N =
        prefill_chunk_blocks while decode streams are resident (their
        inter-token latency is what the budget protects); 2N once the
        most-pressured prefill crosses _PRESSURE_ESCALATE (so a starved
        prefill still converges under decode load); unbounded (-1) when
        nothing is decoding — there is no ITL to protect, finish."""
        n = self._prefill_chunk_blocks()
        if not any(s.active for s in self._slots):
            return -1
        work = [s for s in self._slots if s.prefill is not None]
        peak = max(self._pressure(s) for s in work)
        return 2 * n if peak >= _PRESSURE_ESCALATE else n

    def _advance_prefills(self):
        """Run this boundary's prefill-chunk budget: grants go to the
        most-pressured PREFILLING slot first (re-ranked per grant, so one
        long prompt cannot shadow a later HIGH admission), and requests
        whose final chunk lands activate — their rids are returned and
        their first token surfaces through this step()'s output."""
        finished = []
        if not any(s.prefill is not None for s in self._slots):
            return finished
        budget = self._prefill_budget()
        while budget != 0:
            work = [s for s in self._slots if s.prefill is not None]
            if not work:
                break
            slot = max(work, key=self._pressure)
            if self._prefill_chunk_step(slot):
                finished.append(slot.rid)
            budget -= 1
        return finished

    def _prefill_chunk_step(self, slot):
        """ONE granted chunk: forward the next pool-block-sized prompt
        span through the cached prefill path, pour any block it
        completed, and publish poured full blocks to the prefix tree so
        a mid-prefill admission can already hit them on the chunk
        boundary.  The span [off, off+bs) is a function of the prompt
        alone — never of scheduling — and each chunk keeps its own
        full-chunk attention geometry (the PR-16 PrefillChainSpec
        shape-identity rule), which is why the emitted stream is
        bit-identical to an atomic engine prefilling in
        prefill_chunk=block_size chunks.  Returns True when the prompt
        completed (the slot activated)."""
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import (_model_forward_cached,
                                             prefill_chain_scope)

        st = slot.prefill
        prompt = st.req["prompt"]
        s0 = prompt.shape[1]
        bs = self.block_size
        model = self.model
        try:
            if self._pack is not None and slot.adapter_slot:
                from paddle_tpu.nn.lora import adapter_prefill_scope

                ctx = adapter_prefill_scope(model.model.layers, self._pack,
                                            slot.adapter_slot)
            else:
                ctx = contextlib.nullcontext()
            pf_cfg = self._resolve_prefill_chain()
            with ctx, prefill_chain_scope(pf_cfg), paddle.no_grad():
                chunk = prompt[:, st.off:st.off + bs]
                st.h, st.caches = _model_forward_cached(
                    model.model, paddle.to_tensor(chunk), st.caches,
                    st.off)
                st.off += chunk.shape[1]
            _DECODE_STATS["prefill_chunks"] += 1
            # pour freshly COMPLETED blocks as we go: per-block pour
            # writes the same bytes (and the same per-block quant scales)
            # the atomic pour batches, so the boundary is pure data
            # movement
            while st.poured < st.off // bs:
                self._pour_block(slot, st.poured)
                st.poured += 1
            if self._prefix is not None and st.poured > len(st.matched):
                ns = ((slot.adapter_slot,
                       self._slot_epochs[slot.adapter_slot])
                      if self._pack is not None else None)
                toks = st.req.setdefault(
                    "toks", [int(t) for t in prompt[0]])
                self._prefix.insert(toks[:st.poured * bs],
                                    slot.blocks[:st.poured], ns=ns)
            if st.off < s0:
                return False
            self._finish_prefill(slot)
            return True
        except BaseException:
            # back out like _try_admit: the request is forfeit, the
            # allocator/slot are restored (tree-held poured pages stay
            # cached — they are complete, valid blocks)
            self._cancel_prefill(slot)
            raise

    def _pour_block(self, slot, j):
        """Pour ONE completed prompt block (tokens [j*bs, (j+1)*bs)) from
        the naive prefill caches into the slot's j-th pool page — the
        chunked entry (ops.paged_attention.paged_pour_block)."""
        from paddle_tpu.ops import paged_attention as pa

        bs = self.block_size
        st = slot.prefill
        lo = j * bs
        b = slot.blocks[j]
        for li, (k, v) in enumerate(st.caches):
            kv = jnp.moveaxis(k._value, 1, 2)[0, :, lo:lo + bs]  # [Nkv,bs,H]
            vv = jnp.moveaxis(v._value, 1, 2)[0, :, lo:lo + bs]
            self._kpools[li] = pa.paged_pour_block(self._kpools[li], kv, b)
            self._vpools[li] = pa.paged_pour_block(self._vpools[li], vv, b)
            if self._pool_sharding is not None:
                self._kpools[li] = self._place_pool(self._kpools[li],
                                                    self._pool_sharding)
                self._vpools[li] = self._place_pool(self._vpools[li],
                                                    self._pool_sharding)

    def _finish_prefill(self, slot):
        """Last chunk landed: pour the remainder (the partial tail block
        plus zero-padded future decode pages — exactly the atomic pour's
        coverage from the same offset), derive the first token from the
        final chunk's logits, and activate the slot.  Mirrors
        _try_admit's commit tail."""
        import paddle_tpu as paddle

        st = slot.prefill
        req = st.req
        prompt = req["prompt"]
        s0 = prompt.shape[1]
        bs = self.block_size
        with paddle.no_grad():
            logits_last = self.model._logits(
                st.h[:, -1:, :])._value[0, -1, :]
        first = int(np.asarray(jnp.argmax(logits_last)))
        self._pour(self._kpools, self._vpools, st.caches, slot.blocks, s0,
                   self._nkv, self._head_dim, sharding=self._pool_sharding,
                   start_tok=st.poured * bs)
        slot.active = True
        slot.prefill = None
        slot.seq_len = s0
        slot.max_len = req["max_len"]
        slot.temperature = req["temperature"]
        slot.d_seq_len = 0
        slot.key = np.asarray(
            jax.random.fold_in(jax.random.PRNGKey(req["seed"]),
                               req["nonce"]))
        if slot.temperature > 0.0:
            lg = logits_last.astype(jnp.float32) / slot.temperature
            key = jax.random.fold_in(jnp.asarray(slot.key), 0)
            first = int(np.asarray(jax.random.categorical(key, lg)))
        slot.last_token = first
        slot.generated = [first]
        self._results[slot.rid] = slot.generated
        if self._prefix is not None:
            ns = ((slot.adapter_slot, self._slot_epochs[slot.adapter_slot])
                  if self._pack is not None else None)
            toks = req.setdefault("toks", [int(t) for t in prompt[0]])
            self._prefix.insert(toks, slot.blocks[:s0 // bs], ns=ns)
            if st.matched:
                _DECODE_STATS["prefix_hits"] += 1
                _DECODE_STATS["prefix_hit_tokens"] += len(st.matched) * bs
            else:
                _DECODE_STATS["prefix_misses"] += 1
        _DECODE_STATS["resident_peak"] = max(
            _DECODE_STATS["resident_peak"],
            sum(1 for s in self._slots if s.active))
        _DECODE_STATS["admitted_" + _PRI_NAMES[slot.priority]] += 1
        if self.eos_token_id is not None and first == self.eos_token_id:
            self._finish(slot)
        elif slot.seq_len + 1 >= slot.max_len:
            self._finish(slot)

    def _cancel_prefill(self, slot, requeue=False):
        """Back a PREFILLING slot out: references released through _unref
        (never a direct free — incremental inserts may have handed poured
        pages to the prefix tree, where they stay as reclaimable cached
        pages), the slot cleared.  With requeue=True the original
        submission returns to the queue — re-prefill is deterministic
        (same spans, same bytes), so demotion costs work, never
        correctness."""
        st = slot.prefill
        self._unref(st.fresh)
        self._unref(st.matched)
        if self._pack is not None:
            self._slot_refs[slot.adapter_slot] -= 1
        slot.adapter_slot = 0
        slot.blocks = []
        slot.rid = None
        slot.req = None
        slot.prefill = None
        if requeue:
            self._pending.append(st.req)

    # ------------------------------------------- preemption (parking lot)
    def _preempt_one(self):
        """Evict one LOW-priority resident to unblock a higher-class
        admission.  ACTIVE LOWs park: their pool pages ship host-side
        (serving/snapshot.py park_request_state) and the stream resumes
        bit-identically on re-admission.  PREFILLING LOWs demote back to
        the queue instead — their progress is re-derivable, their pages
        are not yet a stream.  Returns True when something was evicted."""
        if self.draft_model is not None:
            return False
        if not _flags.flag("FLAGS_preempt_low_priority"):
            return False
        victims = [s for s in self._slots
                   if s.active and s.priority >= _PRIORITY["low"]
                   and s.adapter_slot == 0 and s.req is not None]
        if victims:
            # least progress lost first; slot index breaks ties so the
            # choice is deterministic
            v = min(victims,
                    key=lambda s: (len(s.generated), self._slots.index(s)))
            self._park_request(v)
            return True
        pf = [s for s in self._slots
              if s.prefill is not None and s.priority >= _PRIORITY["low"]]
        if pf:
            self._cancel_prefill(pf[0], requeue=True)
            _DECODE_STATS["preemptions"] += 1
            return True
        return False

    def _park_request(self, slot):
        """Preempt an ACTIVE request: its per-request state (slot fields,
        emitted tokens, nonce-derived key) plus its pool pages — verbatim
        pool-native bytes, the same wire face the cluster ships — move to
        the host-side parking lot, and its pool blocks free NOW."""
        from paddle_tpu.serving.snapshot import park_request_state

        rec = park_request_state(self, slot)
        self._parked[slot.rid] = rec
        self._release(slot)
        _DECODE_STATS["preemptions"] += 1
        _DECODE_STATS["parked_requests"] = len(self._parked)

    def _try_unpark(self, rid):
        """Re-admit a parked request: fresh pool blocks, pages placed
        VERBATIM (pool_set_blocks — ship-then-place is bit-exact by
        construction, never a re-quantization), slot state restored.
        The resumed stream continues token-for-token where it parked:
        the sampling key is (seed, nonce) and the per-step fold index is
        len(generated), both request identity.  Returns False on
        transient shortage (slot or pool), leaving the record parked."""
        from paddle_tpu.serving.snapshot import unpark_request_state

        rec = self._parked[rid]
        slot = next((s for s in self._slots
                     if not s.active and s.prefill is None), None)
        if slot is None:
            return False
        if not unpark_request_state(self, slot, rec):
            return False
        del self._parked[rid]
        # live streams alias their slot's generated list — the same
        # invariant _try_admit establishes
        self._results[rid] = slot.generated
        _DECODE_STATS["preempt_readmits"] += 1
        _DECODE_STATS["parked_requests"] = len(self._parked)
        _DECODE_STATS["resident_peak"] = max(
            _DECODE_STATS["resident_peak"],
            sum(1 for s in self._slots if s.active))
        return True

    def _prefix_or_empty(self, kpools, vpools, matched, m_len, n_layers,
                         nkv, head_dim, dtype):
        """Naive-cache seed for a suffix prefill: the matched prefix
        gathered out of `kpools`/`vpools`, or length-0 empties.  One
        builder for the main and draft pools so their prefix-gather
        contracts cannot drift apart."""
        import paddle_tpu as paddle

        if m_len:
            return self._gather_prefix(kpools, vpools, matched, m_len,
                                       nkv, head_dim, dtype)
        return [
            (paddle.zeros([1, 0, nkv, head_dim], dtype=dtype),
             paddle.zeros([1, 0, nkv, head_dim], dtype=dtype))
            for _ in range(n_layers)
        ]

    def _gather_prefix(self, kpools, vpools, blocks, length, nkv, head_dim,
                       dtype):
        """Materialize a matched prefix's K/V as naive-cache Tensors
        ([1, L, Nkv, H] per layer): the suffix prefill attends these
        through the same cross-length path chunked prefill uses.
        Quantized pools dequantize here — gather-side dequant, exactly as
        the decode step does."""
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.ops import paged_attention as pa

        tables = jnp.asarray(np.asarray(blocks, np.int32)[None])
        dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        out = []
        for kc, vc in zip(kpools, vpools):
            kv = pa.paged_gather(kc, tables)[:, :, :length]  # [1,Nkv,L,H]
            vv = pa.paged_gather(vc, tables)[:, :, :length]
            out.append((Tensor(jnp.moveaxis(kv, 1, 2).astype(dt)),
                        Tensor(jnp.moveaxis(vv, 1, 2).astype(dt))))
        return out

    def _pour(self, kpools, vpools, caches, blocks, s0, nkv, head_dim,
              sharding=None, start_tok=0):
        """Scatter naive prefill caches into a request's pool pages.

        start_tok (always block-aligned) skips the prefix-matched region:
        `caches` hold the FULL logical sequence (gathered prefix +
        computed suffix) but only blocks[start_tok//bs:] — the request's
        exclusively owned pages — are written.  Quantized pools get fresh
        per-block-per-head scales here (paged_pour_blocks)."""
        from paddle_tpu.ops import paged_attention as pa

        bs = self.block_size
        b0 = start_tok // bs
        tgt = blocks[b0:]
        n_t = len(tgt)
        pad = b0 * bs + n_t * bs - s0
        idx = jnp.asarray(tgt, jnp.int32)
        for li, (k, v) in enumerate(caches):
            kv = jnp.moveaxis(k._value, 1, 2)[:, :, start_tok:]  # [1,Nkv,S',H]
            vv = jnp.moveaxis(v._value, 1, 2)[:, :, start_tok:]
            if pad:
                kv = jnp.pad(kv, ((0, 0), (0, 0), (0, pad), (0, 0)))
                vv = jnp.pad(vv, ((0, 0), (0, 0), (0, pad), (0, 0)))
            # [1, Nkv, n_t*bs, H] -> n_t x [Nkv, bs, H]
            kv = kv.reshape(nkv, n_t, bs, head_dim).swapaxes(0, 1)
            vv = vv.reshape(nkv, n_t, bs, head_dim).swapaxes(0, 1)
            kpools[li] = pa.paged_pour_blocks(kpools[li], kv, idx)
            vpools[li] = pa.paged_pour_blocks(vpools[li], vv, idx)
            if sharding is not None:
                # keep the pool committed to its head-sharded layout so the
                # decode executable's input shardings stay stable
                kpools[li] = self._place_pool(kpools[li], sharding)
                vpools[li] = self._place_pool(vpools[li], sharding)

    def _finish(self, slot):
        _DECODE_STATS["completed_" + _PRI_NAMES[slot.priority]] += 1
        self._results[slot.rid] = list(slot.generated)
        self._release(slot)

    def adopt_pages(self, prompt_ids, k_blocks, v_blocks, ns=None):
        """Adopt externally prefilled KV pages (a prefill worker's
        shipment — serving/cluster.py) as CACHED prefix pages: pool-native
        page bytes (`ops.paged_attention.pool_get_blocks` dicts, one per
        layer) land verbatim in freshly taken pool blocks, and the prompt's
        full-block chunks enter the radix prefix tree refcount-ZERO —
        resident, reclaimable, and matched by the next `add_request` for
        this prompt exactly like locally cached pages.  Shipping is
        DETERMINISTIC: a prefill worker pours through the same
        `paged_pour_blocks` math over the same full-block forward, so a
        re-dispatched request adopts byte-identical pages and its stream
        is the one the first dispatch would have produced — the cluster's
        bit-exact fail-over contract.  (Versus a purely local prefill of
        the WHOLE prompt, page bytes can differ at XLA reassociation
        level ~1e-9: the forward spans differ, so shape-dependent tiling
        may reassociate — which is why the cluster contract compares
        cluster runs to cluster runs, docs/SERVING_CLUSTER.md.)

        `ns` is the sender's (slot, epoch) adapter namespace — the pack
        slot whose weights poured these pages, pinned at SHIP time.  On
        an adapter engine the pages land in exactly that prefix-cache
        namespace, so a tenant admission under the same adapter matches
        them and other tenants (different K/V!) never cross-match.  A
        STALE epoch — the slot was re-registered/evicted between ship and
        adoption, so this engine no longer serves those weights — drops
        the shipment loudly (lora_stats()["ship_ns_drops"], return 0)
        instead of caching K/V no admission should ever match.  ns=None
        on an adapter engine means the base model: slot 0's namespace,
        whose epoch never moves (slot 0 is the reserved identity).

        Best-effort by contract: pool pressure (after LRU reclaim) or an
        already-cached prefix simply adopts fewer (possibly zero) blocks
        and returns that count — shipping is an optimization; admission
        always works without it.  Geometry mismatches raise."""
        if self._prefix is None:
            raise RuntimeError(
                "adopt_pages needs the prefix cache: shipped pages are "
                "delivered AS cached prefixes (build the engine with "
                "prefix_cache=True; docs/SERVING_CLUSTER.md)")
        if self.draft_model is not None:
            raise RuntimeError(
                "adopt_pages on a speculative engine is not supported: "
                "shipped pages cover the target pools only, and a "
                "draft-pool-less prefix would desynchronize d_seq_len")
        if self._pack is None:
            if ns is not None:
                raise ValueError(
                    "adopt_pages got adapter namespace ns="
                    f"{tuple(ns)} but this engine was built without "
                    "adapters= — adapter-poured K/V must never enter a "
                    "base engine's un-namespaced prefix cache")
        else:
            slot, epoch = (0, self._slot_epochs[0]) if ns is None \
                else (int(ns[0]), int(ns[1]))
            if not 0 <= slot < self._pack.num_slots:
                raise ValueError(
                    f"adopt_pages namespace slot {slot} out of range "
                    f"[0, {self._pack.num_slots}) for this engine's pack")
            if epoch != self._slot_epochs[slot]:
                # pinned at ship time, stale at adoption: the slot was
                # re-registered (or its tenant evicted) in between, so
                # these pages hold K/V of weights this engine no longer
                # serves — strand them loudly, never cache them
                _LORA_STATS["ship_ns_drops"] += 1
                return 0
            ns = (slot, epoch)
        if len(k_blocks) != self._n_layers or len(v_blocks) != self._n_layers:
            raise ValueError(
                f"shipped pages cover {len(k_blocks)}/{len(v_blocks)} "
                f"layers; this engine has {self._n_layers}")
        bs = self.block_size
        n_wire = int(np.asarray(k_blocks[0]["payload"]).shape[0])
        toks = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        n = min(n_wire, len(toks) // bs)
        from paddle_tpu.ops import paged_attention as pa

        want_leaves = {name for name, _a in pa.pool_parts(self._kpools[0])}
        for li in range(self._n_layers):
            for leaves in (k_blocks[li], v_blocks[li]):
                if set(leaves) != want_leaves:
                    # a kind mismatch (bf16 pages into an int8 pool or
                    # vice versa) must be THIS error, not a KeyError deep
                    # in pool_set_blocks — the sender quantized for the
                    # wrong pool kind and retrying cannot help
                    raise ValueError(
                        f"shipped page leaves {sorted(leaves)} != pool "
                        f"kind {sorted(want_leaves)} (layer {li}; "
                        f"kv_cache_dtype mismatch between sender and "
                        "this engine?)")
                got = tuple(np.asarray(leaves["payload"]).shape[1:])
                want = (self._nkv, bs, self._head_dim)
                if got != want:
                    raise ValueError(
                        f"shipped page geometry {got} != pool {want} "
                        f"(layer {li})")
        # only the NOVEL tail needs pool blocks: chunks the tree already
        # holds keep their existing pages (and get LRU-touched)
        matched = self._prefix.match(toks[: n * bs], ns=ns)
        start = len(matched)
        if start >= n:
            return 0
        try:
            fresh = self._alloc(n - start)
        except _PoolExhausted:
            return 0
        for b in fresh:
            self._ref[b] = 0  # cached-but-unreferenced: reclaimable
        idx = jnp.asarray(fresh, jnp.int32)
        for li in range(self._n_layers):
            kb = {name: jnp.asarray(arr)[start:n]
                  for name, arr in k_blocks[li].items()}
            vb = {name: jnp.asarray(arr)[start:n]
                  for name, arr in v_blocks[li].items()}
            self._kpools[li] = pa.pool_set_blocks(self._kpools[li], idx, kb)
            self._vpools[li] = pa.pool_set_blocks(self._vpools[li], idx, vb)
            if self._pool_sharding is not None:
                self._kpools[li] = self._place_pool(self._kpools[li],
                                                    self._pool_sharding)
                self._vpools[li] = self._place_pool(self._vpools[li],
                                                    self._pool_sharding)
        self._prefix.insert(toks[: n * bs], matched + fresh, ns=ns)
        return len(fresh)

    # ------------------------------------------------- fault tolerance
    def snapshot(self, dir, step=None) -> int:
        """Commit a restorable snapshot of this LIVE engine under `dir`
        through the CheckpointManager commit protocol (atomic rename,
        checksummed manifest, SIGKILL matrix — serving/snapshot.py,
        docs/CHECKPOINT.md serving section).  Call between step()s; the
        automatic path (maybe_snapshot) runs at macro-step boundaries
        only.  Returns the committed step tag."""
        from paddle_tpu.serving.snapshot import EngineSnapshot

        store = self._snapshot_store
        if store is None or store.dir != str(dir):
            # one store per engine+dir: its manifest-validity cache makes
            # the per-save retention sweep mtime-cheap instead of
            # re-hashing every retained snapshot's pool bytes
            store = self._snapshot_store = EngineSnapshot(dir)
        return store.save(self, step=step)

    def install_preemption_handler(self, signals=None):
        """SIGTERM-style preemption for serving: the handler only flips a
        flag (async signal context is no place for device syncs or disk
        IO); the next maybe_snapshot() at a macro-step boundary writes
        the final snapshot — the CheckpointManager flag-flip design on
        the serving loop.  Check `preemption_saved` to exit cleanly."""
        import signal as _signal

        if signals is None:
            signals = (_signal.SIGTERM,)

        def _handler(signum, frame):
            self._preempt_requested = True

        for s in signals:
            prev = _signal.signal(s, _handler)
            # re-install keeps the ORIGINAL disposition: recording our
            # own handler as "previous" would make uninstall a no-op and
            # strand SIGTERM on a detached engine forever
            self._prev_handlers.setdefault(s, prev)

    def uninstall_preemption_handler(self):
        import signal as _signal

        for s, prev in self._prev_handlers.items():
            _signal.signal(s, prev)
        self._prev_handlers.clear()

    @property
    def preemption_requested(self) -> bool:
        return self._preempt_requested

    @property
    def preemption_saved(self) -> bool:
        """True once a preemption-triggered snapshot has been committed."""
        return self._preempt_saved

    def maybe_snapshot(self, dir=None, step=None):
        """Snapshot when due — a pending preemption flag, or the periodic
        FLAGS_engine_snapshot_interval macro-step boundary.  step() calls
        this at the END of every macro-step when FLAGS_engine_snapshot_dir
        is set, so snapshots land at boundaries and never mid-dispatch.
        Returns the committed step tag, or None when nothing was due."""
        if self._draining:
            # the drain snapshot IS the handoff state: lame-duck stepping
            # after drain() must not overwrite it (or worse, push it out
            # of retention) with post-handoff boundaries
            return None
        d = dir if dir is not None else _flags.flag("FLAGS_engine_snapshot_dir")
        if not d:
            return None
        due = self._preempt_requested and not self._preempt_saved
        if not due:
            # N boundaries since the last periodic save (not a modulo of
            # the counter: idle boundaries call in without advancing it,
            # and must not re-save the same state every call)
            interval = int(_flags.flag("FLAGS_engine_snapshot_interval"))
            due = (interval > 0 and self._macro_steps > 0
                   and self._macro_steps - self._last_auto_snapshot
                   >= interval)
        if not due:
            return None
        st = self.snapshot(d, step=step)
        self._last_auto_snapshot = self._macro_steps
        if self._preempt_requested:
            self._preempt_saved = True
        return st

    def drain(self, dir=None, step=None) -> int:
        """The migration / elastic-scale-down primitive: commit a final
        snapshot (resident requests, queued admissions, caches, adapter
        state — everything) and CLOSE admissions on this engine.  Returns
        the snapshot step to hand off; `restore_engine` rebuilds a fully
        open engine from it on another process/host/topology.  The
        drained engine may keep stepping its RESIDENTS to completion —
        it never admits again (add_request raises, and the queued
        requests in the snapshot are the restore target's to serve, so
        the lame duck neither admits nor counts them as work; automatic
        maybe_snapshot is disarmed too, so post-handoff boundaries can
        never overwrite or age out the handoff snapshot)."""
        if self._draining and self._drain_step is not None:
            # idempotent: a re-drain (an orchestrator retrying a timed-out
            # handoff) returns the ALREADY-committed handoff step — a
            # second snapshot here would capture lame-duck progress and
            # hand the restore target different state per retry.  Only
            # for the SAME directory: returning a step that does not
            # exist under a new dir would send the restore target to a
            # missing snapshot while the caller believes it committed.
            if dir is not None and str(dir) != self._drain_dir:
                raise ValueError(
                    f"engine already drained to {self._drain_dir!r} "
                    f"(step {self._drain_step}); a re-drain to {dir!r} "
                    "cannot re-capture the handoff state — restore from "
                    "the original directory")
            return self._drain_step
        d = dir if dir is not None else _flags.flag("FLAGS_engine_snapshot_dir")
        if not d:
            raise ValueError(
                "drain() needs a snapshot directory: pass dir= or set "
                "FLAGS_engine_snapshot_dir")
        # in-flight overload-discipline state is the restore target's to
        # serve: PREFILLING slots and parked (preempted) requests demote
        # to queued submissions BEFORE the snapshot — they ride it as
        # pending and replay deterministically from (seed, nonce) on the
        # restored engine (re-prefill spans and pours are identical)
        for s in self._slots:
            if s.prefill is not None:
                self._cancel_prefill(s, requeue=True)
        for rid in list(self._parked):
            self._pending.append(self._parked.pop(rid)["req"])
        _DECODE_STATS["parked_requests"] = len(self._parked)
        self._draining = True
        self._drain_dir = str(d)
        st = self._drain_step = self.snapshot(d, step=step)
        from paddle_tpu.serving.snapshot import _SNAPSHOT_STATS

        _SNAPSHOT_STATS["drains"] += 1
        return st

    # -------------------------------------------------------------- decode
    def _effective_chunk(self) -> int:
        if self._decode_chunk is not None:
            return self._decode_chunk
        return max(1, int(_flags.flag("FLAGS_decode_chunk")))

    def _resolve_decode_chain(self):
        """Consult the schedule searcher for this engine's decode hot
        chain (paged gather → dequant → sdpa core → quant-write; schedule
        search phase 2, docs/SCHEDULE_SEARCH.md) and cache the verdict:
        an ACCEPTED config — served from the per-device-kind AutotuneCache
        with zero re-measurement, or freshly searched (enumerate → prune
        → parity → measure → measured-win gate) on a never-seen geometry
        — makes the compiled macro-step run the chain as ONE fused Pallas
        dispatch per layer per token; anything else keeps the unfused XLA
        ops.  A flag change re-resolves alongside the invalidated step
        executables.

        TP-sharded engines search the MESH spec (schedule search over the
        mesh, ROADMAP item 3): the spec carries the engine's mesh, so its
        verdict caches under the (device kind, mesh shape) key, parity
        gates against the sharded XLA twin, and the adopted kernel builds
        inside shard_map over the committed pool layout.  Before adoption
        the kernel's collectives are statically linted
        (mesh_lint.lint_decode_chain) — a violation is a counted disable,
        never a dispatch.  Engines whose pools ride replicated (head
        counts the mp axis doesn't divide — the constructor's fallback)
        keep the counted mesh skip: there is no head-local layout to fuse
        over."""
        if self._decode_chain_cfg is not _CHAIN_UNSET:
            return self._decode_chain_cfg
        cfg = None
        if (_flags.flag("FLAGS_schedule_search")
                and _flags.flag("FLAGS_schedule_search_decode")):
            mesh = self.mesh
            n_heads = self.model.config.num_attention_heads
            mp = mesh.get_dim_size(self._mp_axis) if mesh is not None else 1
            if mesh is not None and (n_heads % mp or self._nkv % mp):
                _SCHED_DECODE_STATS["decode_chains_mesh_skipped"] += 1
            else:
                from paddle_tpu.ops import decode_chain as _dc

                _SCHED_DECODE_STATS["decode_chains_found"] += 1
                spec = _dc.DecodeChainSpec(
                    batch=self.max_batch,
                    num_heads=n_heads,
                    num_kv_heads=self._nkv,
                    head_dim=self._head_dim,
                    block_size=self.block_size,
                    max_blocks=self._max_blocks_per_seq,
                    num_blocks=self._num_blocks + self.max_batch,
                    kv=self._kv_dtype,
                    dtype=jnp.dtype(
                        jnp.bfloat16
                        if self.model.config.dtype == "bfloat16"
                        else jnp.float32),
                    mesh=mesh,
                    mp_axis=self._mp_axis,
                )
                decision = _dc.ensure_decision(spec)
                adopted = decision.accepted
                if adopted and mesh is not None:
                    from paddle_tpu.static.mesh_lint import lint_decode_chain

                    if lint_decode_chain(spec, decision.config):
                        adopted = False  # named violation → counted disable
                if adopted:
                    cfg = dict(decision.config)
                    _SCHED_DECODE_STATS["decode_chains_accepted"] += 1
                    if mesh is not None:
                        # the live mesh handle rides NON-PERSISTED config
                        # entries (fused_decode_step pops them): the cache
                        # stores the pure schedule, the step builds the
                        # shard_map chain
                        cfg["_mesh"] = mesh
                        cfg["_mp_axis"] = self._mp_axis
                        _SCHED_DECODE_STATS["decode_chains_mesh_fused"] += 1
                else:
                    _SCHED_DECODE_STATS["decode_chains_disabled"] += 1
        self._decode_chain_cfg = cfg
        return cfg

    def _resolve_prefill_chain(self):
        """The chunked-prefill twin of _resolve_decode_chain
        (PrefillChainSpec): engines with a fixed prefill_chunk search the
        canonical mid-prompt geometry — an S=prefill_chunk query chunk
        against a T=2·prefill_chunk cache span — and an accepted config
        makes every DIVISIBLE chunk's attention core run as one K-tiled
        Pallas dispatch under models.llama.prefill_chain_scope; chunks
        the config doesn't tile keep the XLA path.  Single-device
        engines only: mesh engines keep GSPMD prefill (the pour is
        bandwidth-bound on the pool commit, not the attention core).
        INTERLEAVED engines (prefill_chunk_blocks > 0) search their
        actual chunk geometry — one pool block — since every granted
        chunk is exactly block_size tokens."""
        if self._prefill_chain_cfg is not _CHAIN_UNSET:
            return self._prefill_chain_cfg
        cfg = None
        eff = (self.block_size if self._prefill_chunk_blocks() > 0
               else self.prefill_chunk)
        if (eff is not None and self.mesh is None and eff >= 2
                and _flags.flag("FLAGS_schedule_search")
                and _flags.flag("FLAGS_schedule_search_decode")):
            from paddle_tpu.ops import decode_chain as _dc

            _SCHED_DECODE_STATS["prefill_chains_found"] += 1
            spec = _dc.PrefillChainSpec(
                seq=eff,
                kv_len=2 * eff,
                num_heads=self.model.config.num_attention_heads,
                head_dim=self._head_dim,
                dtype=jnp.dtype(
                    jnp.bfloat16
                    if self.model.config.dtype == "bfloat16"
                    else jnp.float32),
            )
            decision = _dc.ensure_decision(spec)
            if decision.accepted:
                cfg = dict(decision.config)
                _SCHED_DECODE_STATS["prefill_chains_accepted"] += 1
            else:
                _SCHED_DECODE_STATS["prefill_chains_disabled"] += 1
        self._prefill_chain_cfg = cfg
        return cfg

    def _build_step(self, chunk: int):
        """One macro-step executable: `chunk` decode tokens per dispatch.

        The single-token step rides a lax.scan INSIDE the jit (pools
        donated), emitting [B, chunk] tokens per dispatch — one host
        round-trip and one device sync amortize over the whole chunk.
        Rows that hit a stop condition mid-chunk flip a `done` mask: their
        remaining writes land on their scratch page (never the shared
        pool) and their lens/fold counters freeze, so the live rows'
        streams stay bit-identical to the per-token path while the host
        discards the masked tail after the dispatch.

        On adapter engines the step takes three extra arguments — the
        per-row slot vector and the pack's A/B + scaling arrays — and
        every decoder layer adds the gathered per-row LoRA delta, so a
        batch mixing tenants (and base rows at slot 0) decodes in this
        one program; swaps change argument VALUES only, never shapes, so
        the executable is reused across them."""
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import (_decode_layers_paged,
                                             _pool_carry, _pool_unpack)

        model = self.model
        state = self._state
        eos = self.eos_token_id
        has_pack = self._pack is not None
        # accepted decode-chain schedule (or None): resolved OUTSIDE the
        # trace, so the compiled program bakes one fixed fused/unfused
        # shape — adoption never changes mid-stream (schedule search
        # phase 2; docs/SCHEDULE_SEARCH.md)
        chain_cfg = self._resolve_decode_chain()

        def step(state_vals, kpools, vpools, tokens, tables, scratch_tables,
                 lens, max_lens, done0, temps, keys, steps, *lora_args):
            if has_pack:
                ad_slots, pack_ab, pack_scaling = lora_args
                row_scale = jnp.take(pack_scaling, ad_slots)  # [B]
            else:
                ad_slots = pack_ab = row_scale = None
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                # carry form ONCE per dispatch: a LayerStack's pools scan
                # as one stacked [N, ...] buffer each — the N-pool concat
                # is paid per dispatch, never per decoded token
                kpools, vpools = _pool_carry(model.model.layers,
                                             kpools, vpools)

                # the body is defined INSIDE the traced step: lax.scan
                # caches body jaxprs by the body's identity, and a shared
                # body would leak one trace's bound-weight tracers into
                # the next trace
                def one(carry, _):
                    tok, kps, vps, lens_c, steps_c, done = carry
                    # finished/inactive lanes park on their scratch page
                    # with lens 1 — same geometry the host gives inactive
                    # slots, so their writes never touch the shared pool
                    tables_eff = jnp.where(done[:, None], scratch_tables,
                                           tables)
                    lens_eff = jnp.where(done, jnp.int32(1), lens_c)
                    with no_grad():
                        h = model.model.embed_tokens(Tensor(tok))
                        cos = model.model.rope_cos._value
                        sin = model.model.rope_sin._value
                        h, kps, vps = _decode_layers_paged(
                            model.model.layers, h, cos, sin, kps, vps,
                            tables_eff, lens_eff, adapters=pack_ab,
                            slots=ad_slots, scaling=row_scale,
                            chain_cfg=chain_cfg)
                        h = model.model.norm(h)
                        logits = model._logits(h)
                    lg = logits._value[:, -1, :]
                    greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    # per-slot temperature sampling inside the SAME
                    # program: fold the slot's generated-token counter
                    # into its key, sample per row, select by the mask
                    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
                    skeys = jax.vmap(jax.random.fold_in)(keys, steps_c)
                    sampled = jax.vmap(jax.random.categorical)(
                        skeys, lg.astype(jnp.float32) / safe_t
                    ).astype(jnp.int32)
                    nxt = jnp.where(temps > 0, sampled, greedy)
                    # mirror of the host stop conditions: EOS, or the
                    # sequence (now lens_c long) leaving no room for one
                    # more token within max_len
                    fin = ((nxt == eos) if eos is not None
                           else jnp.zeros_like(done))
                    new_done = done | fin | (lens_c + 1 >= max_lens)
                    lens_n = jnp.where(done, lens_c, lens_c + 1)
                    steps_n = jnp.where(done, steps_c, steps_c + 1)
                    return (nxt[:, None], kps, vps, lens_n, steps_n,
                            new_done), nxt

                (tok, kpools, vpools, *_), toks = jax.lax.scan(
                    one, (tokens, kpools, vpools, lens, steps, done0),
                    None, length=chunk)
                kpools, vpools = _pool_unpack(model.model.layers,
                                              kpools, vpools)
                return jnp.moveaxis(toks, 0, 1), kpools, vpools
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(step, donate_argnums=(1, 2))

    def _step_avals(self):
        """ShapeDtypeStruct mirror of step()'s exact dispatch signature,
        in argument order.  Device-resident inputs (weights, pools, the
        scratch tables, adapter pack arrays) carry their live shardings so
        an AOT-compiled executable accepts the real committed arrays;
        host-built inputs (tokens/tables/lens/...) are plain avals.  The
        signature is geometry-pure — max_batch, blocks-per-seq, pool
        shapes, pack shape — so two engines built from the same recorded
        geometry produce identical avals (what lets a warm standby carry
        its compiled steps onto a snapshot-restored engine)."""
        def arr_aval(v):
            return jax.ShapeDtypeStruct(v.shape, v.dtype,
                                        sharding=getattr(v, "sharding",
                                                         None))

        B, W = self.max_batch, self._max_blocks_per_seq
        avals = (
            [arr_aval(t._value) for t in self._state],
            jax.tree_util.tree_map(arr_aval, list(self._kpools)),
            jax.tree_util.tree_map(arr_aval, list(self._vpools)),
            jax.ShapeDtypeStruct((B, 1), jnp.int32),     # tokens
            jax.ShapeDtypeStruct((B, W), jnp.int32),     # tables
            arr_aval(self._scratch_tables),
            jax.ShapeDtypeStruct((B,), jnp.int32),       # lens
            jax.ShapeDtypeStruct((B,), jnp.int32),       # max_lens
            jax.ShapeDtypeStruct((B,), jnp.bool_),       # done0
            jax.ShapeDtypeStruct((B,), jnp.float32),     # temps
            jax.ShapeDtypeStruct((B, 2), jnp.uint32),    # keys
            jax.ShapeDtypeStruct((B,), jnp.uint32),      # steps
        )
        if self._pack is not None:
            avals += (jax.ShapeDtypeStruct((B,), jnp.int32),
                      jax.tree_util.tree_map(arr_aval, self._pack.ab),
                      jax.tree_util.tree_map(arr_aval, self._pack.scaling))
        return avals

    def warmup(self, chunks=None, *, prefill=True, adopt=True):
        """Pay trace + XLA compile for this engine's hot executables
        before traffic — the serving analogue of jit.TrainStep.warmup.
        No step runs: the macro-step is lowered from ShapeDtypeStructs
        (state, pools, and host inputs as avals), compiled, and stored in
        the same `_step_fns` table step() consults, so the first real
        dispatch runs a ready executable instead of compiling on the
        serving critical path.  With FLAGS_compilation_cache_dir set the
        compile itself deserializes from the persistent cache — a
        respawned cluster worker warms up in cache-hit time before
        announcing readiness (serving/cluster_worker.py).

        `chunks` lists decode chunk widths to compile (default: the
        effective chunk).  There is no separate tail program to warm: the
        macro-step's done-mask design parks rows that finish mid-chunk on
        their scratch pages in-device, so the one D-token executable IS
        the tail executable.  `prefill=True` additionally runs the
        admission prefill forward for a single-block prompt over empty
        caches (the eager dispatch path keys on prompt length, so this
        warms the one length every full-block admission dispatches;
        longer prompts still compile lazily).  `adopt=True` round-trips
        one scratch page through pool_get_blocks/pool_set_blocks — the
        page-shipping adopt path's gather/scatter programs.

        Speculative engines skip the macro-step warm (they dispatch
        draft/verify programs, not `_step_fns`); prefill/adopt warming
        still applies where supported.  Returns
        {"chunks": [warmed widths], "seconds": wall}."""
        t0 = time.perf_counter()
        warmed: list = []
        if self.draft_model is None:
            todo = sorted({int(c) for c in (
                chunks if chunks is not None else [self._effective_chunk()])})
            for D in todo:
                if D < 1:
                    raise ValueError("decode chunk widths must be >= 1")
                if D not in self._step_fns:
                    self._step_fns[D] = (self._build_step(D)
                                         .lower(*self._step_avals())
                                         .compile())
                warmed.append(D)
        if prefill:
            import paddle_tpu as paddle
            from paddle_tpu.models.llama import _model_forward_cached

            caches = self._prefix_or_empty(
                self._kpools, self._vpools, [], 0, self._n_layers,
                self._nkv, self._head_dim, self.model.config.dtype)
            dummy = np.zeros((1, self.block_size), np.int32)
            with paddle.no_grad():
                _model_forward_cached(self.model.model,
                                      paddle.to_tensor(dummy), caches, 0)
        if adopt and self._prefix is not None and self.draft_model is None \
                and self._pack is None:
            from paddle_tpu.ops import paged_attention as pa

            # one scratch page through the ship-adoption gather/scatter:
            # scratch contents are garbage by design (masked lanes write
            # there), and the poured-back pool is DISCARDED — only the
            # compiled programs persist
            idx = jnp.asarray([self._scratch[0]], jnp.int32)
            for pool in (self._kpools[0], self._vpools[0]):
                leaves = pa.pool_get_blocks(pool, idx)
                pa.pool_set_blocks(pool, idx, dict(leaves))
        return {"chunks": warmed, "seconds": time.perf_counter() - t0}

    def _build_draft_step(self):
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import _decode_layers_paged

        model = self.draft_model
        state = self._d_state

        def dstep(state_vals, kpools, vpools, tokens, tables, lens):
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                with no_grad():
                    h = model.model.embed_tokens(Tensor(tokens))
                    cos = model.model.rope_cos._value
                    sin = model.model.rope_sin._value
                    h, new_k, new_v = _decode_layers_paged(
                        model.model.layers, h, cos, sin, kpools, vpools,
                        tables, lens)
                    h = model.model.norm(h)
                    logits = model._logits(h)
                return (jnp.argmax(logits._value[:, -1, :], axis=-1)
                        .astype(jnp.int32), new_k, new_v)
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(dstep)

    def _build_verify(self):
        from paddle_tpu._core.autograd import no_grad
        from paddle_tpu._core.tensor import Tensor
        from paddle_tpu.models.llama import _decode_layers_paged

        model = self.model
        state = self._state
        has_pack = self._pack is not None

        def verify(state_vals, kpools, vpools, tokens, tables, lens,
                   *lora_args):
            """tokens [B, K+1]; lens INCLUDING the whole chunk; returns
            preds [B, K+1] (greedy next token after each chunk position)
            plus the written pools.  On adapter engines the extra args
            are the per-row slot vector + the pack's A/B and scaling
            (same contract as the plain macro-step): the TARGET verifies
            through each row's adapter even though the draft proposed
            with the base model, so acceptance only ever keeps tokens
            the adapted model would decode."""
            if has_pack:
                ad_slots, pack_ab, pack_scaling = lora_args
                row_scale = jnp.take(pack_scaling, ad_slots)  # [B]
            else:
                ad_slots = pack_ab = row_scale = None
            originals = [t._value for t in state]
            try:
                for t, v in zip(state, state_vals):
                    t._bind(v)
                with no_grad():
                    h = model.model.embed_tokens(Tensor(tokens))
                    cos = model.model.rope_cos._value
                    sin = model.model.rope_sin._value
                    h, new_k, new_v = _decode_layers_paged(
                        model.model.layers, h, cos, sin, kpools, vpools,
                        tables, lens, chunk=True, adapters=pack_ab,
                        slots=ad_slots, scaling=row_scale)
                    h = model.model.norm(h)
                    logits = model._logits(h)
                return (jnp.argmax(logits._value, axis=-1).astype(jnp.int32),
                        new_k, new_v)
            finally:
                for t, v in zip(state, originals):
                    t._bind(v)

        return jax.jit(verify)

    def _spec_step(self):
        """One speculative tick: the draft proposes K tokens per live slot
        (K compiled single-token draft steps, batched over slots), the
        target verifies every chunk in ONE compiled multi-token step, and
        per-slot greedy acceptance emits 1..K+1 tokens.  Rejected tail
        entries in the pools die by lens bookkeeping — pages are
        positional, so rollback costs nothing."""
        if self._draft_fn is None:
            self._draft_fn = self._build_draft_step()
            self._verify_fn = self._build_verify()
        K = self.num_speculative
        B, W = self.max_batch, self._max_blocks_per_seq
        tables = np.zeros((B, W), np.int32)
        last = np.zeros((B, 1), np.int32)
        seq0 = np.zeros((B,), np.int32)
        d0 = np.zeros((B,), np.int32)
        ad_slots = np.zeros((B,), np.int32)
        for i, sl in enumerate(self._slots):
            if sl.active:
                row = list(sl.blocks) + [sl.blocks[-1]] * (W - len(sl.blocks))
                tables[i] = row
                last[i, 0] = sl.last_token
                seq0[i] = sl.seq_len
                d0[i] = sl.d_seq_len
                ad_slots[i] = sl.adapter_slot
            else:
                tables[i] = self._scratch[i]
        tables_j = jnp.asarray(tables)

        # ---- draft proposes K tokens (inactive lanes ride scratch) -----
        # K+1 draft steps: the extra step feeds the LAST proposal so the
        # draft pool always covers its own proposals — acceptance then
        # never needs a per-slot catch-up pass, whatever gets accepted
        d_state = [t._value for t in self._d_state]
        prop_dev = []
        tok = jnp.asarray(last)
        for j in range(K + 1):
            lens_d = jnp.asarray(d0 + 1 + j)
            tok1, dk, dv = self._draft_fn(
                d_state, list(self._d_kpools), list(self._d_vpools),
                tok, tables_j, lens_d)
            self._d_kpools, self._d_vpools = list(dk), list(dv)
            if j < K:
                prop_dev.append(tok1)
                tok = tok1[:, None]  # stays on device: steps pipeline
        _DECODE_STATS["dispatches"] += K + 1
        t_sync = time.perf_counter()
        proposals = np.stack([np.asarray(t) for t in prop_dev], axis=1)
        _DECODE_STATS["sync_seconds"] += time.perf_counter() - t_sync

        # ---- target verifies the whole chunk in one step ---------------
        chunk = np.concatenate([last, proposals], axis=1)  # [B, K+1]
        lens_v = jnp.asarray(seq0 + K + 1)
        lora_args = ()
        if self._pack is not None:
            # the draft proposed base-model tokens; the target verifies
            # through each row's adapter (pack as ARGUMENTS — hot swaps
            # change values, never shapes, like the plain macro-step)
            lora_args = (jnp.asarray(ad_slots), self._pack.ab,
                         self._pack.scaling)
            _LORA_STATS["gather_dispatches"] += 1
        preds, nk, nv = self._verify_fn(
            [t._value for t in self._state],
            list(self._kpools), list(self._vpools),
            jnp.asarray(chunk), tables_j, lens_v, *lora_args)
        self._kpools, self._vpools = list(nk), list(nv)
        _DECODE_STATS["dispatches"] += 1
        t_sync = time.perf_counter()
        preds = np.asarray(preds)  # [B, K+1]
        _DECODE_STATS["sync_seconds"] += time.perf_counter() - t_sync

        # ---- per-slot acceptance + emission ----------------------------
        self._spec_stats["ticks"] += 1
        out = {}
        for i, sl in enumerate(self._slots):
            if not sl.active:
                continue
            accepted = 0
            while accepted < K and preds[i, accepted] == proposals[i, accepted]:
                accepted += 1
            self._spec_stats["proposed"] += K
            self._spec_stats["accepted"] += accepted
            new_toks = [int(t) for t in proposals[i, :accepted]]
            new_toks.append(int(preds[i, accepted]))
            base_seq = sl.seq_len  # pre-round trusted pool coverage
            emitted = []
            finish = False
            for t in new_toks:
                emitted.append(t)
                sl.generated.append(t)
                if self.eos_token_id is not None and t == self.eos_token_id:
                    finish = True
                    break
                # total = prompt + generated = base_seq + 1 + emitted
                if base_seq + 1 + len(emitted) >= sl.max_len:
                    finish = True
                    break
            # trusted pool coverage = prompt + generated[:-1]; the draft
            # pool covers the same prefix (its stale tail dies positionally)
            sl.seq_len = base_seq + len(emitted)
            sl.d_seq_len = sl.seq_len
            sl.last_token = emitted[-1]
            out[sl.rid] = emitted
            self._spec_stats["emitted"] += len(emitted)
            if finish:
                self._finish(sl)
        return out

    def spec_stats(self):
        """Speculative acceptance counters (None on plain engines):
        mean acceptance = accepted/proposed sizes num_speculative_tokens;
        emitted/ticks is the per-tick speedup over plain decode."""
        return None if self.draft_model is None else dict(self._spec_stats)

    def step(self):
        """One macro-step for every live request: D = decode_chunk tokens
        advance in ONE compiled dispatch; requests are admitted/retired
        only here, at macro-step boundaries (stop conditions re-checked on
        the host after the dispatch; a row that stopped mid-chunk had its
        surplus lanes masked onto its scratch page in-device and its
        surplus tokens dropped now).

        Plain engines return {rid: token} when D == 1 and
        {rid: [tok, ...]} when D > 1; SPECULATIVE engines always emit a
        LIST of tokens per request per tick — one accepted run plus the
        target's correction/bonus token.  A request admitted from the
        PENDING QUEUE this step always maps to a list, led by its
        prefill-produced first token (the one add_request returned None
        instead of)."""
        if not self.has_work():
            # an idle engine is still at a boundary: a pending SIGTERM
            # preemption (or an overdue interval) must commit its final
            # snapshot HERE, or a drained-empty serving loop would spin
            # until the orchestrator escalates to SIGKILL
            self.maybe_snapshot()
            return {}
        # macro-step boundary: queued admissions (pool pressure at
        # add_request time) retry before this dispatch; their prefill
        # first tokens (add_request returned None) surface in THIS
        # step's output — always as a list for those rids, even at D=1.
        # A draining engine admits NOTHING: its queue was handed off in
        # the drain snapshot and will be served by the restore target.
        admitted = [] if self._draining else self._admit_pending()
        # interleaved chunked prefill: grant this boundary's budget of
        # block-sized chunks (deadline pressure orders the PREFILLING
        # slots); prompts whose final chunk landed activate NOW and
        # their first token joins this step's output like any queued
        # admission (drain() demoted prefilling slots, so this is a
        # no-op on a lame duck)
        admitted.extend(self._advance_prefills())
        if not any(s.active for s in self._slots):
            # an admitted request may have finished AT admission
            # (EOS / max_new_tokens=1): its first token still surfaces.
            # This IS a macro-step boundary — allocator/results/pending
            # all mutated — so the counter advances and the periodic
            # snapshot interval keeps accruing across such steps
            out = {rid: list(self._results[rid]) for rid in admitted}
            self._macro_steps += 1
            self.maybe_snapshot()
            return out
        t_start = time.perf_counter()
        if self.draft_model is not None:
            out = self._spec_step()
            _DECODE_STATS["tokens"] += sum(len(v) for v in out.values())
            _DECODE_STATS["macro_steps"] += 1
            _DECODE_STATS["step_seconds"] += time.perf_counter() - t_start
            # prepend AFTER the stats: prefill firsts aren't decode tokens
            self._merge_admitted(out, admitted)
            self._macro_steps += 1
            self.maybe_snapshot()  # boundary: no-op without a snapshot dir
            return out
        D = self._effective_chunk()
        step_fn = self._step_fns.get(D)
        if step_fn is None:
            step_fn = self._step_fns[D] = self._build_step(D)

        B, W = self.max_batch, self._max_blocks_per_seq
        tokens = np.zeros((B, 1), np.int32)
        tables = np.zeros((B, W), np.int32)
        lens = np.ones((B,), np.int32)
        max_lens = np.zeros((B,), np.int32)
        done0 = np.ones((B,), bool)
        temps = np.zeros((B,), np.float32)
        keys = np.zeros((B, 2), np.uint32)
        steps = np.zeros((B,), np.uint32)
        ad_slots = np.zeros((B,), np.int32)
        for i, s in enumerate(self._slots):
            if s.active:
                tokens[i, 0] = s.last_token
                row = list(s.blocks) + [s.blocks[-1]] * (W - len(s.blocks))
                tables[i] = row
                lens[i] = s.seq_len + 1  # includes the token being decoded
                max_lens[i] = s.max_len
                done0[i] = False
                temps[i] = s.temperature
                keys[i] = s.key
                steps[i] = len(s.generated)  # fold index for this request
                ad_slots[i] = s.adapter_slot
            else:
                tables[i] = self._scratch[i]  # park masked lanes off-pool
                lens[i] = 1

        lora_args = ()
        if self._pack is not None:
            # pack contents ride as ARGUMENTS (not closed-over constants):
            # register_adapter's scatter produces new arrays of identical
            # shape, so a swap changes values only and this same compiled
            # step serves every tenant mix
            lora_args = (jnp.asarray(ad_slots), self._pack.ab,
                         self._pack.scaling)
            _LORA_STATS["gather_dispatches"] += 1
        nxt, new_k, new_v = step_fn(
            [t._value for t in self._state],
            list(self._kpools), list(self._vpools),
            jnp.asarray(tokens), jnp.asarray(tables),
            self._scratch_tables, jnp.asarray(lens),
            jnp.asarray(max_lens), jnp.asarray(done0),
            jnp.asarray(temps), jnp.asarray(keys), jnp.asarray(steps),
            *lora_args,
        )
        self._kpools = list(new_k)
        self._vpools = list(new_v)
        t_sync = time.perf_counter()
        nxt = np.asarray(nxt)  # [B, D] — the one device sync per chunk
        _DECODE_STATS["dispatches"] += 1
        _DECODE_STATS["macro_steps"] += 1
        _DECODE_STATS["last_chunk"] = D
        _DECODE_STATS["sync_seconds"] += time.perf_counter() - t_sync

        out = {}
        for i, s in enumerate(self._slots):
            if not s.active:
                continue
            rid = s.rid  # _finish() clears the slot's rid on retirement
            emitted = []
            for j in range(D):
                tok = int(nxt[i, j])
                s.seq_len += 1
                s.last_token = tok
                s.generated.append(tok)
                emitted.append(tok)
                if (self.eos_token_id is not None
                        and tok == self.eos_token_id) or (
                            s.seq_len + 1 >= s.max_len):
                    self._finish(s)
                    break
            out[rid] = emitted if D > 1 else emitted[0]
            _DECODE_STATS["tokens"] += len(emitted)
        _DECODE_STATS["step_seconds"] += time.perf_counter() - t_start
        self._merge_admitted(out, admitted)
        self._macro_steps += 1
        self.maybe_snapshot()  # boundary: no-op without a snapshot dir
        return out

    def _merge_admitted(self, out, admitted):
        """Prepend queue-admitted requests' prefill first tokens to this
        step's output.  Those rids always map to a LIST (even at D=1):
        the queued-admission case is new surface, so no existing caller
        sees the shape change."""
        for rid in admitted:
            first = self._results[rid][0]
            got = out.get(rid)
            if got is None:
                out[rid] = [first]
            elif isinstance(got, list):
                out[rid] = [first] + got
            else:
                out[rid] = [first, got]


from .snapshot import (EngineSnapshot, restore_engine,  # noqa: E402
                       reset_snapshot_stats, snapshot_stats)
