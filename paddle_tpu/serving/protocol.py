"""The cluster wire protocol AS DATA (docs/SERVING_CLUSTER.md topology).

Until PR 19 the router/replica/prefill/standby protocol lived in three
places that could drift independently: the if/elif dispatch chains in
serving/cluster.py and serving/cluster_worker.py, a hand-written markdown
table in docs/SERVING_CLUSTER.md, and the SIGKILL test matrix's implicit
expectations.  This module makes the protocol a single machine-readable
source of truth:

- ``MESSAGES`` — every wire message with its direction(s), payload fields
  and one-line meaning.  docs/SERVING_CLUSTER.md embeds the table
  ``wire_table_markdown()`` renders (a test regenerates and diffs it, so
  the doc cannot drift from the code).
- ``ROLE_STATES`` / ``TRANSITIONS`` — per-role state machines: which
  messages a router / decode replica / prefill worker / warm standby may
  legally receive and emit in each lifecycle phase.
- ``INVARIANTS`` — the named safety properties the protocol exists to
  uphold.  ``static/protocol_lint.py`` checks every one of them in every
  reachable state of an abstract 5-process model (docs/PROTOCOL_LINT.md).

Dispatch runs THROUGH these tables (the dead-flag-lint trick applied to a
protocol): `EngineCluster` binds its ``_ev_<msg>`` event handlers via
``bind_handlers`` at construction, and cluster_worker binds its per-role
``_decode_msg_<msg>`` / ``_prefill_msg_<msg>`` / ``_standby_msg_<msg>``
functions the same way at import.  Both directions are asserted — a spec
message with no handler AND a handler with no spec message each raise
``ProtocolSpecError`` before any process is forked — so removing either
side fails loudly and the spec cannot rot.

This module is deliberately dependency-free (stdlib only): the router
imports it before jax exists in any worker, and the static-analysis tier
(static/protocol_lint.py, tools/lint_protocol.py) consumes it without
touching an accelerator.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "Message",
    "MESSAGES",
    "ROLES",
    "ROLE_STATES",
    "TRANSITIONS",
    "INVARIANTS",
    "ProtocolSpecError",
    "messages_to",
    "messages_from",
    "bind_handlers",
    "wire_table_markdown",
    "validate_spec",
]


ROLES = ("router", "decode", "prefill", "standby")


class ProtocolSpecError(RuntimeError):
    """The protocol spec and the code disagree: a spec message without a
    bound handler, a handler outside the spec, or an internally
    inconsistent table.  Raised at EngineCluster construction / worker
    import — always BEFORE any process forks."""


@dataclass(frozen=True)
class Message:
    """One wire message: pickled dict ``{"t": name, **fields}``."""

    name: str
    src: tuple       # sender role(s)
    dst: tuple       # receiver role(s)
    fields: tuple    # payload field names beyond "t"
    meaning: str     # one-liner for the generated wire table

    def direction(self) -> str:
        return f"{'/'.join(self.src)} → {'/'.join(self.dst)}"


# --------------------------------------------------------------- the wire
# Order is the docs-table order: router->worker traffic first, then
# worker->router reports — keep new messages in their direction group.
MESSAGES = (
    Message("submit", ("router",), ("decode",),
            ("rid", "prompt", "max_new", "temperature", "seed", "priority",
             "adapter", "nonce"),
            "serve this request with the ROUTER-assigned nonce (and SLO "
            "class, and LoRA adapter name) journaled at acceptance"),
    Message("ship_begin", ("router",), ("decode",),
            ("sid", "rid", "tokens", "n_blocks", "n_layers", "ns"),
            "forwarded prefill shipment opens: stage `n_blocks` pool-native "
            "K/V pages for these prompt tokens under adapter namespace "
            "`ns` ((slot, epoch), None = base model)"),
    Message("ship_block", ("router",), ("decode",),
            ("sid", "i", "k", "v"),
            "one shipped K/V page (pool-native leaves, one block per "
            "message)"),
    Message("ship_end", ("router",), ("decode",),
            ("sid",),
            "shipment complete: adopt the staged pages as refcount-zero "
            "cached prefix pages"),
    Message("ship_abort", ("router",), ("decode",),
            ("sid",),
            "the shipping prefill worker died; drop the partial staging"),
    Message("drain", ("router",), ("decode",),
            (),
            "graceful scale-down: snapshot, close admissions, finish "
            "residents, hand queued requests home"),
    Message("stop", ("router",), ("decode", "prefill", "standby"),
            (),
            "clean exit (answered with `bye`)"),
    Message("prefill", ("router",), ("prefill",),
            ("rid", "sid", "prompt", "n_blocks", "adapter", "ns"),
            "compute + ship the prompt's full-block K/V pages (through "
            "adapter `adapter`'s weights when set, stamping namespace "
            "`ns`)"),
    Message("promote", ("router",), ("standby",),
            ("snapshot_dir", "snapshot_interval"),
            "claim a dead replica's snapshot dir and become its decode "
            "replica"),
    Message("ready", ("standby",), ("router",),
            ("warmed", "warmup_s", "cache_hits", "cache_misses"),
            "warmup finished; this standby is promotion-eligible (warmed "
            "ends its boot grace)"),
    Message("resume", ("decode", "standby"), ("router",),
            ("rids", "warmed", "warmup_s", "cache_hits", "cache_misses"),
            "which requests this (possibly snapshot-restored) engine owns, "
            "plus its boot warm report"),
    Message("tokens", ("decode",), ("router",),
            ("rid", "start", "toks"),
            "token run at absolute stream position `start` (re-emitted "
            "overlaps must merge bit-for-bit)"),
    Message("done", ("decode",), ("router",),
            ("rid", "n", "hit_toks"),
            "request complete after `n` delivered tokens (`hit_toks` = "
            "this engine's prefix-cache hit-token delta since its last "
            "report, aggregated cluster-wide by the router)"),
    Message("requeue", ("decode",), ("router",),
            ("rid",),
            "a draining replica refuses a submit; the router re-dispatches"),
    Message("drained", ("decode",), ("router",),
            ("queued",),
            "drain report: these queued (never-started) requests migrate "
            "to survivors"),
    Message("page_begin", ("prefill",), ("router",),
            ("sid", "rid", "tokens", "n_blocks", "n_layers", "ns"),
            "shipment opens (relayed to the target replica as "
            "`ship_begin`, adapter namespace `ns` included)"),
    Message("page_block", ("prefill",), ("router",),
            ("sid", "i", "k", "v"),
            "one computed K/V page (relayed as `ship_block`)"),
    Message("page_end", ("prefill",), ("router",),
            ("sid",),
            "shipment complete (relayed as `ship_end`)"),
    Message("shipped", ("prefill",), ("router",),
            ("rid", "n_blocks"),
            "ship finished; the router now submits the request to the "
            "target replica"),
    Message("bye", ("decode", "prefill", "standby"), ("router",),
            (),
            "clean exit acknowledgement"),
    Message("fatal", ("decode", "prefill", "standby"), ("router",),
            ("err",),
            "unrecoverable worker error (treated as death)"),
)

_BY_NAME = {m.name: m for m in MESSAGES}


def messages_to(role: str):
    """Spec messages `role` receives (its inbound dispatch surface)."""
    return tuple(m for m in MESSAGES if role in m.dst)


def messages_from(role: str):
    """Spec messages `role` emits."""
    return tuple(m for m in MESSAGES if role in m.src)


# ------------------------------------------------------- role state machines
# Events: "recv:<msg>" / "send:<msg>" for wire traffic, bare names for
# internal lifecycle steps (boot, idle-drained, shutdown).  The model
# checker walks these; validate_spec() proves the recv/send alphabets
# match MESSAGES exactly, so the machines cannot name phantom traffic.
ROLE_STATES = {
    "router": ("replaying", "serving", "stopped"),
    "decode": ("booting", "serving", "draining", "exiting", "exited"),
    "prefill": ("booting", "serving", "exiting", "exited"),
    # a promoted standby ENTERS the decode machine at "serving": its
    # post-promotion traffic is decode traffic, not standby traffic
    "standby": ("booting", "parked", "restoring", "serving", "exiting",
                "exited"),
}

TRANSITIONS = {
    "router": {
        # construction: replay the intake journal, then serve
        ("replaying", "boot"): "serving",
        ("serving", "recv:ready"): "serving",
        ("serving", "recv:resume"): "serving",
        ("serving", "recv:tokens"): "serving",
        ("serving", "recv:done"): "serving",
        ("serving", "recv:requeue"): "serving",
        ("serving", "recv:drained"): "serving",
        ("serving", "recv:bye"): "serving",
        ("serving", "recv:fatal"): "serving",
        ("serving", "recv:page_begin"): "serving",
        ("serving", "recv:page_block"): "serving",
        ("serving", "recv:page_end"): "serving",
        ("serving", "recv:shipped"): "serving",
        ("serving", "send:submit"): "serving",
        ("serving", "send:prefill"): "serving",
        ("serving", "send:ship_begin"): "serving",
        ("serving", "send:ship_block"): "serving",
        ("serving", "send:ship_end"): "serving",
        ("serving", "send:ship_abort"): "serving",
        ("serving", "send:drain"): "serving",
        ("serving", "send:promote"): "serving",
        ("serving", "send:stop"): "serving",
        ("serving", "shutdown"): "stopped",
    },
    "decode": {
        # readiness = the resume report (AOT warmup already paid)
        ("booting", "send:resume"): "serving",
        ("booting", "send:fatal"): "exited",
        ("serving", "recv:submit"): "serving",
        ("serving", "recv:ship_begin"): "serving",
        ("serving", "recv:ship_block"): "serving",
        ("serving", "recv:ship_end"): "serving",
        ("serving", "recv:ship_abort"): "serving",
        ("serving", "send:tokens"): "serving",
        ("serving", "send:done"): "serving",
        ("serving", "recv:drain"): "draining",
        ("serving", "recv:stop"): "exiting",
        ("serving", "send:fatal"): "exited",
        ("draining", "send:drained"): "draining",
        # a submit racing the drain verdict bounces back to the router
        ("draining", "recv:submit"): "draining",
        ("draining", "send:requeue"): "draining",
        ("draining", "recv:ship_begin"): "draining",
        ("draining", "recv:ship_block"): "draining",
        ("draining", "recv:ship_end"): "draining",
        ("draining", "recv:ship_abort"): "draining",
        ("draining", "send:tokens"): "draining",
        ("draining", "send:done"): "draining",
        ("draining", "recv:stop"): "exiting",
        ("draining", "residents-finished"): "exiting",
        ("draining", "send:fatal"): "exited",
        ("exiting", "send:bye"): "exited",
    },
    "prefill": {
        ("booting", "boot"): "serving",
        ("booting", "send:fatal"): "exited",
        ("serving", "recv:prefill"): "serving",
        ("serving", "send:page_begin"): "serving",
        ("serving", "send:page_block"): "serving",
        ("serving", "send:page_end"): "serving",
        ("serving", "send:shipped"): "serving",
        ("serving", "recv:stop"): "exiting",
        ("serving", "send:fatal"): "exited",
        ("exiting", "send:bye"): "exited",
    },
    "standby": {
        ("booting", "send:ready"): "parked",
        ("booting", "send:fatal"): "exited",
        ("parked", "recv:promote"): "restoring",
        ("parked", "recv:stop"): "exiting",
        ("parked", "send:fatal"): "exited",
        # promotion claims the victim's streams via ONE resume report,
        # then the decode machine takes over at "serving"
        ("restoring", "send:resume"): "serving",
        ("restoring", "send:fatal"): "exited",
        ("exiting", "send:bye"): "exited",
    },
}


# ---------------------------------------------------------- named invariants
# The safety contract, by name.  static/protocol_lint.py checks each in
# EVERY reachable state of the abstract cluster model; counterexample
# traces name the violated invariant (docs/PROTOCOL_LINT.md).
INVARIANTS = {
    "journal-before-dispatch":
        "an accepted rid is fsynced to the intake journal BEFORE any "
        "dispatch for it reaches a ring — a router crash can never lose "
        "an accepted request",
    "no-double-serve":
        "an accepted rid is never actively served by two live replicas "
        "at once (one canonical owner; re-dispatch only after death, "
        "drain, or an explicit requeue)",
    "no-lost-request":
        "an accepted rid always completes: every quiescent state of the "
        "cluster has all accepted requests done — crashes re-dispatch, "
        "never drop",
    "nonce-before-first-token":
        "a rid's nonce is assigned (journaled with the submit) before "
        "its first token is emitted — stream identity precedes the "
        "stream",
    "backpressure-not-death":
        "a ring TimeoutError is backpressure, never a death verdict: "
        "only BrokenPipeError (a destroyed ring) may mark a worker dead",
    "promotion-claims-once":
        "a standby promotion claims a victim replica's streams exactly "
        "once — one resume report, no second claimant",
    "warmed-ends-boot-grace":
        "a worker announcing warmed=True is judged on the steady-state "
        "miss budget from that report on (FailureDetector.mark_warmed "
        "ends its boot grace)",
}


# --------------------------------------------------------- handler binding
def bind_handlers(role: str, lookup, *, prefix: str, label: str = None):
    """Bind `role`'s inbound spec messages to handlers in `lookup`
    (a name->object mapping: module globals, or an instance's attrs).

    Both directions are enforced — the dead-flag-lint trick applied to a
    protocol:

    - every spec message with dst `role` must resolve to a callable named
      ``prefix + message`` (a spec row nobody implements fails loudly);
    - every `lookup` name starting with ``prefix`` must be a spec message
      (a handler the spec no longer names is dead code wearing a live
      wire's uniform).

    Returns the dispatch dict {message name -> handler}.  Raises
    ProtocolSpecError — at EngineCluster construction / worker import,
    always before any fork."""
    label = label or f"{role} dispatch"
    expected = {m.name for m in messages_to(role)}
    bound = {}
    for name in sorted(expected):
        fn = lookup.get(prefix + name)
        if not callable(fn):
            raise ProtocolSpecError(
                f"{label}: spec message {name!r} (dst={role}) has no "
                f"handler {prefix + name!r} — every spec transition must "
                "bind to a real handler (serving/protocol.py)")
        bound[name] = fn
    for key in sorted(lookup):
        if not key.startswith(prefix) or not callable(lookup.get(key)):
            continue
        if key[len(prefix):] not in expected:
            raise ProtocolSpecError(
                f"{label}: handler {key!r} does not correspond to any "
                f"spec message with dst={role} — every handler must "
                "appear in the spec (serving/protocol.py)")
    return bound


def handler_lookup(obj, prefix: str):
    """An instance/class's ``prefix*`` attributes as a bind_handlers
    lookup (dir() walk: inherited handlers count too)."""
    return {n: getattr(obj, n) for n in dir(obj) if n.startswith(prefix)}


# ------------------------------------------------------------ doc generation
def wire_table_markdown() -> str:
    """The docs/SERVING_CLUSTER.md wire-protocol table, generated from
    MESSAGES — one row per message, direction groups in spec order.  The
    doc embeds this between wire-protocol markers and a test regenerates
    and diffs it, so prose can never drift from the dispatch tables."""
    lines = ["| direction | message | payload | meaning |",
             "|---|---|---|---|"]
    for m in MESSAGES:
        payload = f"`{', '.join(m.fields)}`" if m.fields else "—"
        lines.append(
            f"| {m.direction()} | `{m.name}` | {payload} | {m.meaning} |")
    return "\n".join(lines)


# ------------------------------------------------------------ spec self-check
def validate_spec():
    """Internal consistency of the tables themselves: directions name
    real roles, state machines only use declared states, and each role's
    recv/send alphabet in TRANSITIONS matches MESSAGES exactly.  Runs at
    import — an inconsistent spec never loads."""
    seen = set()
    for m in MESSAGES:
        if m.name in seen:
            raise ProtocolSpecError(f"duplicate message {m.name!r}")
        seen.add(m.name)
        for r in m.src + m.dst:
            if r not in ROLES:
                raise ProtocolSpecError(
                    f"message {m.name!r} names unknown role {r!r}")
    for role, table in TRANSITIONS.items():
        states = set(ROLE_STATES[role])
        recvs, sends = set(), set()
        for (state, event), nxt in table.items():
            if state not in states or nxt not in states:
                raise ProtocolSpecError(
                    f"{role}: transition ({state!r}, {event!r}) -> "
                    f"{nxt!r} uses an undeclared state")
            if event.startswith("recv:"):
                recvs.add(event[5:])
            elif event.startswith("send:"):
                sends.add(event[5:])
        want_recv = {m.name for m in messages_to(role)}
        want_send = {m.name for m in messages_from(role)}
        if recvs != want_recv:
            raise ProtocolSpecError(
                f"{role}: state machine receives {sorted(recvs)} but the "
                f"message table says {sorted(want_recv)}")
        if sends != want_send:
            raise ProtocolSpecError(
                f"{role}: state machine sends {sorted(sends)} but the "
                f"message table says {sorted(want_send)}")


validate_spec()
