"""Disaggregated serving cluster: router tier + engine replicas as real OS
processes (ROADMAP item 2; docs/SERVING_CLUSTER.md).

`EngineCluster` is the ROUTER process object: it hosts the native TCPStore
for rendezvous + heartbeats, creates one ShmRing pair per worker, spawns N
decode replicas (each a `GenerationEngine` in its own process —
serving/cluster_worker.py) and optionally M dedicated prefill workers, and
drives everything from a single-threaded poll loop.  The design is
failure-first:

- **Acceptance is durable.**  submit() journals the request (prompt,
  decode opts, router-assigned nonce) to a fsynced intake log BEFORE any
  dispatch; a SIGKILL of the router or any worker can never lose an
  accepted request.
- **Identity is the stream.**  The router assigns the submit-time nonce,
  so the sampled (and greedy) token stream is a pure function of the
  request — whichever replica serves it, in whatever batch mix.  That is
  what makes fail-over BIT-EXACT: a re-dispatched request regenerates the
  same tokens, and the router's per-position merge verifies the overlap.
- **Death is detected, not assumed.**  Replicas bump a per-replica
  heartbeat counter in the store from a background thread; the router's
  miss-threshold detector (FLAGS_cluster_heartbeat_ms /
  FLAGS_cluster_heartbeat_misses) declares death, with child-exit as the
  fast path (the router is the parent).  On death: the replica's prefix
  pages leave the cluster index, its accepted-but-unfinished requests
  re-dispatch — replayed from the intake log onto survivors, or claimed
  by a respawned replacement restored from the dead replica's last
  boundary `EngineSnapshot` (serving/snapshot.py) when one exists.
- **Pages ship in pool-native bytes.**  Prefill workers pour K/V through
  the SAME `paged_pour_blocks` math the engine uses and ship the pool's
  own leaves (`pool_get_blocks`), so int8 pools ship int8 payload + f32
  scales — about half the wire bytes of bf16 — and shipping is
  deterministic: a re-dispatched request re-ships byte-identical pages.
  The decode replica adopts them as refcount-zero cached prefix pages;
  admission prefix-matches them and prefills only the suffix tail.
- **Scale-down is drain.**  `scale_down(idx)` drains the replica (PR 13's
  snapshot + closed admissions): residents finish on the lame duck, its
  queued requests come home and re-dispatch — no request is ever served
  to the client twice (the router's canonical stream is the only output).

Every store/ring operation rides timeouts + capped exponential backoff
with jitter (`router.retry_backoff`).  Crash injection for the test
matrix: a `kill="point:nth"` spec SIGKILLs the router at named points, and
the worker spec carries the same for replicas/prefill workers
(tests/test_serving_cluster_crash.py).
"""

from __future__ import annotations

import json
import os
import pickle
import signal
import subprocess
import sys
import time
import uuid

from paddle_tpu._core import flags as _flags
from paddle_tpu.serving import protocol as _protocol
from paddle_tpu.serving.router import (FailureDetector, IntakeLog,
                                       RequestRouter, cluster_adapter_table,
                                       retry_backoff)

__all__ = ["EngineCluster", "cluster_stats", "reset_cluster_stats"]


# ---------------------------------------------------------------- telemetry
# Cluster counters (profiler.cluster_stats() reads them — the serving-owns-
# the-counters contract): replicas_alive is a GAUGE of live decode
# replicas; heartbeats_missed counts heartbeat periods that elapsed with
# no counter advance (each missed period once, not per poll); redispatches
# counts requests re-routed after a death/drain; pages_shipped counts KV
# pages forwarded prefill->decode; ship_bytes their wire bytes;
# ship_retries counts backoff retries + re-ships on the shipping path;
# drain_migrations counts queued requests handed back by drained replicas.
# Warm-start tier (docs/SERVING_CLUSTER.md): standbys_warm is a GAUGE of
# standby workers that reported ready; promotions counts standbys re-keyed
# into dead replica slots; warmups / warmup_seconds count worker AOT warm
# reports and their wall; respawn_compile_hits/misses are the persistent
# compile-cache counters reported by RESPAWNED (gen>1) workers — hits > 0
# is the asserted warm-respawn contract, not an assumption.
_CLUSTER_STATS = {
    "replicas_alive": 0,
    "heartbeats_missed": 0,
    "redispatches": 0,
    "respawns": 0,
    "pages_shipped": 0,
    "ship_bytes": 0,
    "ship_retries": 0,
    "drain_migrations": 0,
    "standbys_warm": 0,
    "promotions": 0,
    "warmups": 0,
    "warmup_seconds": 0.0,
    "respawn_compile_hits": 0,
    "respawn_compile_misses": 0,
    # prefix-cache hit tokens aggregated across decode replicas (relayed
    # as deltas on `done`); nonzero after a shipped-page adoption is the
    # asserted cross-host (and cross-tenant-isolation) cache contract
    "prefix_hit_tokens": 0,
}

# gauges describe LIVE cluster state, not traffic: reset never zeros them
_GAUGES = ("replicas_alive", "standbys_warm")

# the data-plane kind of the most recent EngineCluster in this process —
# a label, not a counter (reset leaves it, like the gauges)
_CURRENT_TRANSPORT = {"kind": "shm"}


def cluster_stats(reset: bool = False) -> dict:
    """Disaggregated-serving cluster counters (docs/SERVING_CLUSTER.md):
    live decode replicas, heartbeat periods missed, request re-dispatches
    after death/drain, KV pages (and bytes) shipped prefill->decode, ship
    retries, drain-migrated queued requests, and the warm-start tier —
    warm standbys (gauge), standby promotions, worker AOT warmups (count
    + wall seconds), and the persistent compile-cache hit/miss counts
    respawned workers reported at boot.  `transport` labels the data
    plane of the most recent cluster; `tcp_bytes`/`reconnects`/
    `frames_sent`/`frames_recv` are the socket-transport counters
    (serving/transport.py — all zero under shm).  Zeros when no cluster
    ran this process."""
    from paddle_tpu.serving.transport import transport_stats

    out = dict(_CLUSTER_STATS)
    out["transport"] = _CURRENT_TRANSPORT["kind"]
    out.update(transport_stats(reset=reset))
    if reset:
        reset_cluster_stats(_transport_too=False)
    return out


def reset_cluster_stats(_transport_too: bool = True):
    for k in _CLUSTER_STATS:
        if k not in _GAUGES:
            _CLUSTER_STATS[k] = 0.0 if k == "warmup_seconds" else 0
    if _transport_too:
        from paddle_tpu.serving.transport import reset_transport_stats

        reset_transport_stats()


# ------------------------------------------------------------ kill injection
class _KillSpec:
    """Crash injection: SIGKILL this process when `hit(point)` reaches the
    named point for the nth time — the cluster mirror of
    FLAGS_checkpoint_kill_point (spec "point" or "point:nth")."""

    def __init__(self, spec):
        self.point, self.nth = None, 1
        if spec:
            parts = str(spec).split(":")
            self.point = parts[0]
            if len(parts) > 1:
                self.nth = int(parts[1])
        self._count = 0

    def hit(self, point):
        if self.point != point:
            return
        self._count += 1
        if self._count == self.nth:
            sys.stdout.flush()
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)


def _encode(msg) -> bytes:
    return pickle.dumps(msg, protocol=4)


def _decode(data):
    return pickle.loads(data)


class _Worker:
    """Router-side handle of one spawned worker process."""

    __slots__ = ("role", "idx", "gen", "proc", "logf", "ring_in",
                 "ring_out", "hb_key", "alive", "draining")

    def __init__(self, role, idx, gen, proc, logf, ring_in, ring_out,
                 hb_key):
        self.role = role
        self.idx = idx
        self.gen = gen
        self.proc = proc
        self.logf = logf
        self.ring_in = ring_in    # router -> worker
        self.ring_out = ring_out  # worker -> router
        self.hb_key = hb_key
        self.alive = True
        self.draining = False

    @property
    def key(self):
        return (self.role, self.idx)


class EngineCluster:
    """Router + N decode replicas (+ M prefill workers) as OS processes.

        cluster = EngineCluster("model_defs.py:tiny_llama", num_replicas=2,
                                workdir="/tmp/c1",
                                engine_kwargs={"max_batch": 2, ...})
        cluster.submit("r1", prompt_ids, max_new_tokens=8)
        cluster.serve()                  # poll until every request is done
        cluster.result("r1")             # canonical token stream
        cluster.shutdown()

    `model_spec` is "module:factory" or "path/to/file.py:factory"; every
    worker process calls the factory to build the (deterministically
    seeded) model — weights ride process-local construction or the
    training checkpoint tier, never the wire.  Re-instantiating with the
    same `workdir` after a router death REPLAYS the intake log: completed
    streams are served from the journal, unfinished requests re-dispatch,
    and stale worker processes from the previous incarnation are swept.
    """

    def __init__(self, model_spec, num_replicas=2, num_prefill=0,
                 engine_kwargs=None, *, workdir, heartbeat_ms=None,
                 miss_threshold=None, snapshot_interval=0, respawn=True,
                 ring_mb=16, kill=None, worker_kill=None, standby=None,
                 warmup=True, transport=None, adapters=None):
        """worker_kill: {(role, idx): "point:nth"} crash-injection specs
        forwarded to specific workers; kill: the ROUTER's own spec.
        transport: the data-plane kind, "shm" (process-shared rings,
        single box) or "tcp" (length-framed TcpRing sockets with
        endpoints published through the TCPStore control tier —
        serving/transport.py); None -> FLAGS_cluster_transport.  Both
        carry the same producer/consumer contract, so every fail-over
        path below is transport-agnostic.  adapters: [(name, rank,
        alpha, seed), ...] — deterministic LoRA adapter specs every
        worker registers IN ORDER at boot (adapter weights never ride
        the wire, the same construction-identity story as the model
        factory), giving each adapter an identical (slot, epoch)
        namespace across the fleet so shipped pages adopt into the
        right per-tenant prefix namespace.
        snapshot_interval > 0 arms per-replica boundary snapshots
        (FLAGS_engine_snapshot_interval inside the worker), which is what
        enables restore-based fail-over instead of replay-from-scratch.
        standby: warm standby tier size (None -> FLAGS_cluster_standby) —
        pre-forked workers that already paid import + trace + compile and
        park until a decode replica dies, when one is PROMOTED into the
        dead slot (claiming its snapshot directory) instead of paying a
        cold respawn; a consumed/dead standby is backfilled
        asynchronously.  warmup=False skips worker AOT warmup (engines
        compile lazily at first step, the pre-warm-start behaviour)."""
        from paddle_tpu import _native

        if not _native.AVAILABLE:
            raise RuntimeError(
                "EngineCluster needs the native TCPStore/ShmRing runtime "
                "(paddle_tpu/_native); no C++ toolchain was available")
        self.model_spec = str(model_spec)
        self.engine_kwargs = dict(engine_kwargs or {})
        self.workdir = str(workdir)
        os.makedirs(os.path.join(self.workdir, "logs"), exist_ok=True)
        self.heartbeat_ms = int(
            heartbeat_ms if heartbeat_ms is not None
            else _flags.flag("FLAGS_cluster_heartbeat_ms"))
        self.miss_threshold = int(
            miss_threshold if miss_threshold is not None
            else _flags.flag("FLAGS_cluster_heartbeat_misses"))
        self.snapshot_interval = int(snapshot_interval)
        self.respawn = bool(respawn)
        self.standby = int(standby if standby is not None
                           else _flags.flag("FLAGS_cluster_standby"))
        self.warmup = bool(warmup)
        self.ring_bytes = int(ring_mb) << 20
        self.transport_kind = str(
            transport if transport is not None
            else _flags.flag("FLAGS_cluster_transport"))
        self.adapters = [tuple(a) for a in (adapters or [])]
        self._adapter_ns = cluster_adapter_table(self.adapters)
        if self.adapters:
            names = [str(a[0]) for a in self.adapters]
            if len(set(names)) != len(names):
                raise ValueError(
                    f"duplicate cluster adapter names {names}: the "
                    "deterministic (slot, epoch) namespace needs one slot "
                    "per name")
            ranks = {int(a[1]) for a in self.adapters}
            if len(ranks) != 1:
                raise ValueError(
                    f"cluster adapters carry mixed ranks {sorted(ranks)}; "
                    "AdapterPack geometry is rank-uniform — serve "
                    "mixed-rank tenants from separate clusters")
            # every worker engine needs a pack of matching geometry; an
            # explicit adapters engine kwarg wins (caller knows better)
            self.engine_kwargs.setdefault(
                "adapters", {"rank": ranks.pop(),
                             "max_adapters": len(self.adapters)})
        self._kill = _KillSpec(kill)
        self._worker_kill = dict(worker_kill or {})
        self._ns = f"c{uuid.uuid4().hex[:8]}"  # per-incarnation namespace

        # ---- spec <-> handler binding, BEFORE any fork ------------------
        # Dispatch is table-driven through serving/protocol.py, both
        # directions asserted here: every spec message with dst=router
        # must bind to an _ev_* method and every _ev_* method must appear
        # in the spec; the worker module's per-role tables bind the same
        # way at its import.  Removing a handler or a spec row fails
        # loudly at construction — the spec cannot rot.
        self._handlers = _protocol.bind_handlers(
            "router", _protocol.handler_lookup(self, "_ev_"),
            prefix="_ev_", label="EngineCluster event dispatch")
        from paddle_tpu.serving import cluster_worker as _worker_mod

        _worker_mod.handler_tables()  # binds (and asserts) all 3 roles

        # ---- rendezvous store (the router hosts it) --------------------
        self._store_srv = _native.TCPStoreServer()
        self._store = _native.TCPStoreClient(port=self._store_srv.port)
        from paddle_tpu.serving import transport as _transport

        self._transport = _transport.get_transport(
            self.transport_kind, store=self._store)
        _CURRENT_TRANSPORT["kind"] = self.transport_kind

        # ---- router restart: sweep the previous incarnation ------------
        self._pidfile = os.path.join(self.workdir, "pids.json")
        self._sweep_stale_workers()

        bs = int(self.engine_kwargs.get("block_size", 16))
        self.block_size = bs
        log_path = os.path.join(self.workdir, "intake.jsonl")
        had_log = os.path.exists(log_path)
        self.router = RequestRouter(bs, log_path=log_path,
                                    adapter_ns=self._adapter_ns)
        if had_log:
            self.router.restore(IntakeLog.replay(log_path))

        self.detector = FailureDetector(
            self.heartbeat_ms, self.miss_threshold,
            on_miss=lambda n: _CLUSTER_STATS.__setitem__(
                "heartbeats_missed",
                _CLUSTER_STATS["heartbeats_missed"] + n))

        self._workers: dict = {}        # (role, idx) -> _Worker
        self._gens: dict = {}           # (role, idx) -> spawn generation
        self._shipping: dict = {}       # rid -> {"pw", "target", "sid"}
        self._pending_claims: dict = {} # decode idx -> set(rids)
        self._standby_ready: set = set()  # standby keys that reported ready
        self._standby_seq = 0             # monotonic standby idx allocator
        self._stopped = False
        # router restart over a live workdir: replicas spawned with a
        # RESTORABLE snapshot will CLAIM their resident requests via
        # their resume reports — replay-dispatching those same rids
        # before the reports arrive would double-dispatch them, so the
        # unassigned backlog holds until every restorable replica has
        # resumed (or died, or the boot deadline passed)
        self._awaiting_resume: set = set()
        self._resume_deadline = 0.0
        from paddle_tpu.serving.snapshot import EngineSnapshot

        for i in range(int(num_replicas)):
            if (os.path.isdir(self._snap_dir(i))
                    and EngineSnapshot(self._snap_dir(i)).latest_step()
                    is not None):
                self._awaiting_resume.add(i)
            self._spawn("decode", i, restore=True)
        for i in range(int(num_prefill)):
            self._spawn("prefill", i)
        for _ in range(self.standby):
            self._spawn("standby", self._next_standby_idx())
        if self._awaiting_resume:
            self._resume_deadline = (time.monotonic()
                                     + self.detector.boot_grace_s)
        else:
            self.router_replay_dispatch()

    # ------------------------------------------------------------ plumbing
    def _snap_dir(self, idx):
        return os.path.join(self.workdir, f"replica{idx}")

    def _next_standby_idx(self):
        # standby idxs are never reused: a promoted standby keeps its
        # rings and hb store key while serving under a DECODE key, so a
        # recycled ("standby", i) would collide with the promoted one
        self._standby_seq += 1
        return self._standby_seq - 1

    def _sweep_stale_workers(self):
        """A restarted router inherits the previous incarnation's orphaned
        workers (the old router died; its children did not).  They are
        recorded in the pidfile; any that still look like cluster workers
        are SIGKILLed before fresh ones spawn — two replica sets serving
        one intake log would double-serve."""
        try:
            with open(self._pidfile) as f:
                stale = json.load(f)
        except (OSError, ValueError):
            return
        for _name, pid in stale.items():
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmd = f.read()
                if b"cluster_worker" not in cmd:
                    continue  # pid reused by something else: leave it be
                os.kill(int(pid), signal.SIGKILL)
            except (OSError, ValueError):
                continue
        try:
            os.remove(self._pidfile)
        except OSError:
            pass

    def _write_pidfile(self):
        pids = {f"{w.role}{w.idx}": w.proc.pid
                for w in self._workers.values() if w.alive}
        tmp = self._pidfile + ".tmp"
        with open(tmp, "w") as f:
            json.dump(pids, f)
        os.replace(tmp, self._pidfile)

    def _spawn(self, role, idx, restore=False):
        import paddle_tpu

        gen = self._gens.get((role, idx), 0) + 1
        self._gens[(role, idx)] = gen
        if gen > 1:
            _CLUSTER_STATS["respawns"] += 1
        base = f"/pc_{self._ns}_{role}{idx}g{gen}"
        ring_in = self._transport.create(base + "_in", self.ring_bytes)
        ring_out = self._transport.create(base + "_out", self.ring_bytes)
        hb_key = f"{self._ns}/hb/{role}{idx}"
        spec = {
            "role": role, "idx": idx, "gen": gen,
            "store_port": self._store_srv.port,
            "ring_in": base + "_in", "ring_out": base + "_out",
            "transport": self.transport_kind,
            "adapters": [list(a) for a in self.adapters],
            "hb_key": hb_key, "heartbeat_ms": self.heartbeat_ms,
            "model": self.model_spec, "engine": self.engine_kwargs,
            "snapshot_dir": self._snap_dir(idx) if role == "decode" else "",
            "snapshot_interval": self.snapshot_interval,
            "restore": bool(restore),
            "warmup": self.warmup,
            # crash injection targets the ORIGINAL process only: a
            # replacement re-armed with the same spec would re-kill
            # itself forever and the matrix would test nothing but churn
            "kill": (self._worker_kill.get((role, idx), "")
                     if gen == 1 else ""),
        }
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(paddle_tpu.__file__)))
        env = dict(os.environ)
        env["PADDLE_CLUSTER_SPEC"] = json.dumps(spec)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        logf = open(os.path.join(self.workdir, "logs",
                                 f"{role}{idx}.g{gen}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.cluster_worker"],
            env=env, stdout=logf, stderr=subprocess.STDOUT)
        w = _Worker(role, idx, gen, proc, logf, ring_in, ring_out, hb_key)
        self._workers[(role, idx)] = w
        self.detector.track((role, idx))
        if role == "decode":
            self.router.add_replica(idx)
        self._write_pidfile()
        self._update_alive_gauge()
        return w

    def _update_alive_gauge(self):
        _CLUSTER_STATS["replicas_alive"] = sum(
            1 for w in self._workers.values()
            if w.role == "decode" and w.alive and not w.draining)

    def _live_decode(self):
        return [w.idx for w in self._workers.values()
                if w.role == "decode" and w.alive and not w.draining]

    def _live_prefill(self):
        return [w for w in self._workers.values()
                if w.role == "prefill" and w.alive]

    def _push(self, worker, msg, shipping=False):
        """Ring push with the shared timeout+backoff+jitter contract.
        A poisoned/closed ring (the peer died mid-operation) surfaces as
        BrokenPipeError — the only push failure that means DEATH.  A
        TimeoutError means backpressure (a full ring behind a long
        macro-step or a first compile): callers must retry/re-route the
        MESSAGE, never declare the worker dead for it."""
        data = _encode(msg)

        def once():
            worker.ring_in.push(data, timeout_ms=250)

        retry_backoff(
            once, timeout_s=60.0, retry_on=(TimeoutError,),
            on_retry=(lambda _e: _CLUSTER_STATS.__setitem__(
                "ship_retries", _CLUSTER_STATS["ship_retries"] + 1))
            if shipping else None)
        return len(data)

    # -------------------------------------------------------------- intake
    def submit(self, rid, prompt, max_new_tokens=16, temperature=0.0,
               seed=0, priority="normal", adapter=None):
        """Accept (durably journal) and dispatch one request.  Idempotent
        per rid: resubmitting a known id neither re-journals nor
        re-dispatches — the first acceptance pinned its nonce and its
        stream.  ``priority`` is the SLO class ("high"/"normal"/"low")
        journaled with the request and forwarded to the worker engine's
        admission scheduler.  ``adapter`` names one of the cluster's
        construction-time adapters (the ``adapters=`` specs) to serve
        this request with; an unknown name raises BEFORE anything is
        journaled — a replayed journal must never carry a request no
        worker can serve."""
        if adapter is not None and adapter not in self._adapter_ns:
            raise KeyError(
                f"adapter {adapter!r} is not a cluster adapter "
                f"(have {sorted(self._adapter_ns)}); adapters are fixed "
                "at EngineCluster construction (adapters=[(name, rank, "
                "alpha, seed), ...])")
        known = self.router.request(rid) is not None
        opts = dict(max_new=int(max_new_tokens),
                    temperature=float(temperature), seed=int(seed),
                    priority=str(priority))
        if adapter is not None:
            opts["adapter"] = str(adapter)
        self.router.submit(rid, [int(t) for t in prompt], **opts)
        self._kill.hit("router-after-accept")
        if not known:
            self._dispatch(rid)

    def router_replay_dispatch(self):
        """Dispatch every journal-replayed request that is unfinished and
        unowned (router restart).  Requests a restored replica claims via
        its resume report keep their owner instead."""
        for rid in self.router.unassigned():
            # a replayed request with delivered tokens is a true
            # re-dispatch (its first serve died with the old router)
            self._dispatch(
                rid, redispatch=bool(self.router.request(rid).tokens))

    def _dispatch(self, rid, redispatch=False):
        req = self.router.request(rid)
        live = self._live_decode()
        if not live:
            raise RuntimeError(
                "no live decode replicas (all dead/draining and respawn "
                "disabled) — the cluster cannot serve")
        ns = self.router.ns_of(req)
        target = self.router.pick_replica(req.prompt, among=live, ns=ns)
        if redispatch:
            _CLUSTER_STATS["redispatches"] += 1
            self._shipping.pop(rid, None)
        pws = self._live_prefill()
        full_blocks = (len(req.prompt) - 1) // self.block_size
        if pws and full_blocks >= 1:
            # least-outstanding prefill worker (idx as tie-break): a
            # fixed lowest-idx pick would serialize every shipment
            # through worker 0 and make num_prefill>1 pure overhead
            in_flight = {}
            for s in self._shipping.values():
                in_flight[s["pw"]] = in_flight.get(s["pw"], 0) + 1
            pw = min(pws, key=lambda w: (in_flight.get(w.key, 0), w.idx))
            sid = f"{rid}#{uuid.uuid4().hex[:6]}"
            self.router.assign(rid, target, shipped=True)
            self._shipping[rid] = {"pw": pw.key, "target": target,
                                   "sid": sid, "begun": False}
            try:
                self._push(pw, {"t": "prefill", "rid": rid, "sid": sid,
                                "prompt": req.prompt,
                                "n_blocks": full_blocks,
                                "adapter": req.opts.get("adapter"),
                                "ns": ns}, shipping=True)
                return
            except BrokenPipeError:
                self._on_worker_dead(pw.key)
                self._shipping.pop(rid, None)
            except (TimeoutError, ConnectionError):
                # saturated prefill ring: skip shipping for this request
                # and fall through to the direct path — backpressure on a
                # live worker is never a death verdict
                self._shipping.pop(rid, None)
                _CLUSTER_STATS["ship_retries"] += 1
        # direct path: the replica prefills locally
        self.router.assign(rid, target)
        self._submit_to(target, req)

    def _submit_to(self, idx, req):
        w = self._workers[("decode", idx)]
        try:
            self._push(w, {"t": "submit", "rid": req.rid,
                           "prompt": req.prompt,
                           "max_new": req.opts.get("max_new", 16),
                           "temperature": req.opts.get("temperature", 0.0),
                           "seed": req.opts.get("seed", 0),
                           "priority": req.opts.get("priority", "normal"),
                           "adapter": req.opts.get("adapter"),
                           "nonce": req.nonce})
        except BrokenPipeError:
            self._on_worker_dead(w.key)
        except (TimeoutError, ConnectionError):
            # backpressure, not death: the submit never entered the
            # ring, so releasing the owner re-dispatches it later —
            # the failure detector alone decides who is dead
            self.router.unassign(req.rid)

    # ------------------------------------------------------------- polling
    def poll(self):
        """One router turn: drain every worker's event ring, forward ship
        traffic, detect failures (heartbeats + child exit), respawn and
        re-dispatch.  Single-threaded on purpose — every state transition
        is ordered, so the kill matrix enumerates real interleavings."""
        for w in list(self._workers.values()):
            if not w.alive:
                continue
            self._drain_events(w)
        self._detect_failures()
        self._dispatch_unassigned()

    def _drain_events(self, w):
        while True:
            try:
                data = w.ring_out.pop(timeout_ms=1)
            except TimeoutError:
                return
            except BrokenPipeError:
                self._on_worker_dead(w.key)
                return
            if data is None:
                return
            self._on_event(w, _decode(data))

    def _note_warm_report(self, w, msg):
        """Fold one worker boot report (resume/ready) into the warm-start
        telemetry.  A warmed worker's compiles are behind it, so its
        heartbeat is judged on the steady-state budget immediately — no
        boot grace left to hide a stall in."""
        if msg.get("warmed"):
            self.detector.mark_warmed(w.key)
            _CLUSTER_STATS["warmups"] += 1
            _CLUSTER_STATS["warmup_seconds"] += float(
                msg.get("warmup_s") or 0.0)
        if w.gen > 1:
            _CLUSTER_STATS["respawn_compile_hits"] += int(
                msg.get("cache_hits") or 0)
            _CLUSTER_STATS["respawn_compile_misses"] += int(
                msg.get("cache_misses") or 0)

    def _on_event(self, w, msg):
        """Table-driven event dispatch: the handler set is BOUND to the
        protocol spec at construction (serving/protocol.py), so a message
        outside the spec is a protocol violation, not a silent drop."""
        try:
            handler = self._handlers[msg["t"]]
        except KeyError:
            raise _protocol.ProtocolSpecError(
                f"router received message {msg.get('t')!r} from "
                f"{w.role}{w.idx} — not a spec message with dst=router "
                "(serving/protocol.py)") from None
        handler(w, msg)

    # Every inbound spec message binds to one _ev_<message> method below
    # (and every _ev_* method must be a spec message — both directions
    # asserted at construction, before any fork).
    def _ev_ready(self, w, msg):
        # a standby finished its warmup and parked: eligible for
        # promotion from now on
        self._note_warm_report(w, msg)
        if w.role == "standby" and w.alive:
            self._standby_ready.add(w.key)
            _CLUSTER_STATS["standbys_warm"] = len(self._standby_ready)

    def _ev_resume(self, w, msg):
        self._note_warm_report(w, msg)
        self._awaiting_resume.discard(w.idx)
        claims = self._pending_claims.pop(w.idx, set())
        for rid in msg["rids"]:
            req = self.router.request(rid)
            if req is not None and not req.done:
                self.router.assign(rid, w.idx)
                claims.discard(rid)
        # rids the replacement did NOT resurrect (accepted after its
        # last snapshot boundary) fall back to intake-log replay
        for rid in sorted(claims):
            if not self.router.request(rid).done:
                self._dispatch(rid, redispatch=True)

    def _ev_tokens(self, w, msg):
        self.router.on_tokens(msg["rid"], msg["start"], msg["toks"])
        self._kill.hit("router-mid-serving")

    def _ev_done(self, w, msg):
        # hit_toks is a watermark DELTA and the wire is at-least-once:
        # a `done` redelivered whole after a TcpRing drop must not
        # double-count it, so the add rides first-completion only.
        if self.router.on_done(msg["rid"], msg["n"]):
            _CLUSTER_STATS["prefix_hit_tokens"] += int(msg.get("hit_toks") or 0)

    def _ev_requeue(self, w, msg):
        req = self.router.request(msg["rid"])
        if req is not None and not req.done:
            self._dispatch(msg["rid"], redispatch=True)

    def _ev_drained(self, w, msg):
        w.draining = True
        self._update_alive_gauge()
        migrated = self.router.on_drained(w.idx, msg["queued"])
        _CLUSTER_STATS["drain_migrations"] += len(migrated)
        for rid in migrated:
            self._dispatch(rid, redispatch=True)

    def _ev_bye(self, w, msg):
        w.alive = False
        self.detector.forget(w.key)
        self._standby_ready.discard(w.key)
        _CLUSTER_STATS["standbys_warm"] = len(self._standby_ready)
        self._update_alive_gauge()

    def _ev_page_begin(self, w, msg):
        self._forward_ship(w, msg)

    def _ev_page_block(self, w, msg):
        self._forward_ship(w, msg)

    def _ev_page_end(self, w, msg):
        self._forward_ship(w, msg)

    def _ev_shipped(self, w, msg):
        state = self._shipping.pop(msg["rid"], None)
        if state is not None:
            req = self.router.request(msg["rid"])
            self._submit_to(state["target"], req)

    def _ev_fatal(self, w, msg):
        self._on_worker_dead(w.key)

    def _forward_ship(self, pw, msg):
        """Relay one prefill-worker page message into the target decode
        replica's ring (star topology: the router is the only ring
        producer a worker ever sees, so ship traffic and submits arrive
        in one total order — ship_end always precedes the submit)."""
        state = next((s for s in self._shipping.values()
                      if s["sid"] == msg["sid"]), None)
        if state is None:
            return  # aborted ship: drop the straggler
        tgt = self._workers.get(("decode", state["target"]))
        if tgt is None or not tgt.alive:
            return
        fwd = dict(msg)
        fwd["t"] = {"page_begin": "ship_begin", "page_block": "ship_block",
                    "page_end": "ship_end"}[msg["t"]]
        try:
            n = self._push(tgt, fwd, shipping=True)
        except BrokenPipeError:
            self._on_worker_dead(tgt.key)
            return
        except (TimeoutError, ConnectionError):
            # the target's ring is saturated: abandon this shipment (the
            # decode side drops incomplete staging) and serve the request
            # by direct submit — local prefill instead of shipped pages
            rid = next((r for r, s in self._shipping.items()
                        if s["sid"] == msg["sid"]), None)
            if rid is not None:
                self._shipping.pop(rid, None)
                _CLUSTER_STATS["ship_retries"] += 1
                req = self.router.request(rid)
                if req is not None and not req.done:
                    self._submit_to(state["target"], req)
            return
        state["begun"] = True
        if msg["t"] == "page_block":
            _CLUSTER_STATS["pages_shipped"] += 1
            _CLUSTER_STATS["ship_bytes"] += n

    def _detect_failures(self):
        for w in list(self._workers.values()):
            if not w.alive:
                continue
            try:
                hb = self._store.add(w.hb_key, 0)
            except OSError:
                hb = -1
            self.detector.observe(w.key, hb)
            # fast path: the router is the parent — a SIGKILLed child is
            # visible immediately, no need to wait out the miss threshold
            if w.proc.poll() is not None:
                self._on_worker_dead(w.key)
        for key in self.detector.dead_ranks():
            if key in self._workers and self._workers[key].alive:
                self._on_worker_dead(key)

    def _promote_standby(self, idx):
        """Claim a warm standby for dead decode slot `idx`.  The standby
        keeps its process, rings and heartbeat store key; only its
        router-side identity changes — the _Worker handle is re-keyed to
        ("decode", idx) and handed the dead replica's snapshot directory,
        which it restores (resident requests and all) before reporting
        resume.  Promotion is NOT a respawn: no process spawns, so the
        respawns counter stays put and the consumed standby is backfilled
        asynchronously.  Returns True when a standby took the slot."""
        while self._standby_ready:
            skey = min(self._standby_ready)  # oldest idx: FIFO-ish
            self._standby_ready.discard(skey)
            _CLUSTER_STATS["standbys_warm"] = len(self._standby_ready)
            s = self._workers.get(skey)
            if s is None or not s.alive:
                continue
            try:
                self._push(s, {"t": "promote",
                               "snapshot_dir": self._snap_dir(idx),
                               "snapshot_interval": self.snapshot_interval})
            except (BrokenPipeError, TimeoutError, ConnectionError):
                self._on_worker_dead(skey)
                continue
            # re-key the handle: same process, new cluster identity
            del self._workers[skey]
            self.detector.forget(skey)
            s.role, s.idx = "decode", idx
            self._workers[("decode", idx)] = s
            self._gens[("decode", idx)] = (
                self._gens.get(("decode", idx), 0) + 1)
            self.detector.track(("decode", idx))
            self.detector.mark_warmed(("decode", idx))
            self.router.add_replica(idx)
            _CLUSTER_STATS["promotions"] += 1
            self._write_pidfile()
            self._update_alive_gauge()
            if self.respawn and not self._stopped:
                self._spawn("standby", self._next_standby_idx())
            return True
        return False

    def _on_worker_dead(self, key):
        w = self._workers.get(key)
        if w is None or not w.alive:
            return
        w.alive = False
        self.detector.forget(key)
        if w.role == "decode":
            # a restorable replica that died before resuming can no
            # longer claim the replay backlog — release its hold
            self._awaiting_resume.discard(w.idx)
        try:
            if w.proc.poll() is None:
                w.proc.kill()
        except OSError:
            pass
        for ring in (w.ring_in, w.ring_out):
            try:
                ring.destroy()
            except OSError:
                pass
        self._write_pidfile()
        self._update_alive_gauge()
        if w.role == "standby":
            # a dead standby serves nobody: just backfill the tier so the
            # next decode death still finds a warm candidate
            self._standby_ready.discard(key)
            _CLUSTER_STATS["standbys_warm"] = len(self._standby_ready)
            if self.respawn and not self._stopped:
                self._spawn("standby", self._next_standby_idx())
            return
        if w.role == "prefill":
            # abort in-flight ships from this worker, then re-route them
            for rid, state in list(self._shipping.items()):
                if state["pw"] != key:
                    continue
                tgt = self._workers.get(("decode", state["target"]))
                if state["begun"] and tgt is not None and tgt.alive:
                    try:
                        self._push(tgt, {"t": "ship_abort",
                                         "sid": state["sid"]})
                    except (BrokenPipeError, TimeoutError, ConnectionError):
                        pass
                self._shipping.pop(rid, None)
                _CLUSTER_STATS["ship_retries"] += 1
                if not self.router.request(rid).done:
                    self._dispatch(rid, redispatch=True)
            if self.respawn:
                self._spawn("prefill", w.idx)
            return
        # ---- decode replica death --------------------------------------
        orphans = self.router.on_replica_dead(w.idx)
        for rid in orphans:
            self._shipping.pop(rid, None)
        was_draining = w.draining
        from paddle_tpu.serving.snapshot import EngineSnapshot

        restorable = (self.respawn and not was_draining
                      and os.path.isdir(self._snap_dir(w.idx))
                      and EngineSnapshot(
                          self._snap_dir(w.idx)).latest_step() is not None)
        promoted = False
        if self.respawn and not was_draining:
            # warm standby first — it already paid import + trace +
            # compile, so promotion beats respawn to first token; cold
            # (well, cache-warmed) respawn is the fallback
            promoted = self._promote_standby(w.idx)
            if not promoted:
                self._spawn("decode", w.idx, restore=True)
        if promoted or restorable:
            # let the restored replacement CLAIM what its snapshot holds;
            # unclaimed orphans re-dispatch when its resume report lands
            # (union, not overwrite: a replacement that dies pre-resume
            # must not drop the claims of the generation before it)
            self._pending_claims[w.idx] = (
                set(orphans) | self._pending_claims.get(w.idx, set()))
        else:
            for rid in orphans:
                self._dispatch(rid, redispatch=True)

    def _dispatch_unassigned(self):
        if self._awaiting_resume:
            # restored replicas may still claim these rids (router
            # restart): hold the backlog until every restorable replica
            # has reported (resume), left (death), or overslept the grace
            if time.monotonic() < self._resume_deadline:
                return
            self._awaiting_resume.clear()
        for rid in self.router.unassigned():
            if rid in self._shipping:
                continue
            if any(rid in claims for claims in
                   self._pending_claims.values()):
                continue
            self._dispatch(rid, redispatch=True)

    # ------------------------------------------------------------- serving
    def serve(self, timeout_s=300.0, poll_s=0.002):
        """Poll until every accepted request has completed (or raise at
        the deadline with the stragglers named)."""
        deadline = time.monotonic() + timeout_s
        while True:
            if not self.router.unfinished():
                return
            self.poll()
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"cluster serve timed out with unfinished requests "
                    f"{self.router.unfinished()[:8]}")
            time.sleep(poll_s)

    def result(self, rid):
        return self.router.result(rid)

    def results(self):
        return {r: self.router.result(r)
                for r in sorted(self.router._reqs)}

    # ---------------------------------------------------------- scale-down
    def scale_down(self, idx, timeout_s=120.0):
        """Graceful drain of decode replica `idx`: snapshot + closed
        admissions on the worker (PR 13 drain), queued requests migrate
        to survivors, residents finish on the lame duck, the process
        exits cleanly.  Blocks until the drain report arrives."""
        w = self._workers.get(("decode", idx))
        if w is None or not w.alive:
            raise ValueError(f"no live decode replica {idx}")
        if len(self._live_decode()) <= 1:
            raise RuntimeError(
                "refusing to drain the LAST live replica — queued "
                "requests would have nowhere to migrate")
        self._push(w, {"t": "drain"})
        deadline = time.monotonic() + timeout_s
        while not w.draining:
            self.poll()
            if time.monotonic() >= deadline:
                raise TimeoutError(f"replica {idx} never reported drained")
            time.sleep(0.002)

    # ------------------------------------------------------------ shutdown
    def shutdown(self):
        if self._stopped:
            return
        self._stopped = True
        from paddle_tpu.distributed.launch.main import terminate_procs

        live = [w for w in self._workers.values() if w.alive]
        for w in live:
            try:
                self._push(w, {"t": "stop"})
            except (BrokenPipeError, TimeoutError, ConnectionError, OSError):
                pass
        # the launcher's stop-cleanly-then-forcefully helper (elastic tier)
        terminate_procs([(w.proc, w.logf) for w in live], grace_s=5)
        for w in self._workers.values():
            w.alive = False
            for ring in (w.ring_in, w.ring_out):
                try:
                    ring.destroy()
                except OSError:
                    pass
        # the bye events may never drain: zero the standby gauge here
        self._standby_ready.clear()
        _CLUSTER_STATS["standbys_warm"] = 0
        self._update_alive_gauge()
        if self.router.log is not None:
            self.router.log.close()
        try:
            self._store.close()
            self._store_srv.stop()
        except OSError:
            pass
        try:
            os.remove(self._pidfile)
        except OSError:
            pass

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
