"""paddle.summary (reference python/paddle/hapi/model_summary.py): per-layer
output shapes + parameter counts via forward hooks."""

from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    hooks = []

    def register(layer, prefix):
        subs = dict(layer.named_children()) if hasattr(layer, "named_children") else {}
        if not subs:
            def hook(l, inputs, output, prefix=prefix):
                out = output[0] if isinstance(output, (tuple, list)) else output
                shape = list(out.shape) if isinstance(out, Tensor) else "?"
                n_params = int(sum(np.prod(p.shape) for p in l.parameters(include_sublayers=False)))
                rows.append((prefix or type(l).__name__, type(l).__name__, shape, n_params))

            hooks.append(layer.register_forward_post_hook(hook))
        for name, sub in subs.items():
            register(sub, f"{prefix}.{name}" if prefix else name)

    register(net, "")

    try:
        if input is not None:
            x = input if isinstance(input, (list, tuple)) else [input]
        else:
            sizes = input_size if isinstance(input_size, list) and isinstance(input_size[0], (list, tuple)) else [input_size]
            dts = dtypes if isinstance(dtypes, (list, tuple)) else [dtypes] * len(sizes)
            x = [paddle.zeros(list(s), dtype=dt or "float32") for s, dt in zip(sizes, dts)]
        was_training = net.training
        net.eval()
        try:
            from paddle_tpu._core.autograd import no_grad

            with no_grad():
                net(*x)
        finally:
            if was_training:
                net.train()
    finally:
        for h in hooks:
            h.remove()

    total = int(sum(np.prod(p.shape) for p in net.parameters()))
    trainable = int(sum(np.prod(p.shape) for p in net.parameters() if not p.stop_gradient))

    width = 90
    lines = ["-" * width]
    lines.append(f"{'Layer (type)':<40}{'Output Shape':<30}{'Param #':>12}")
    lines.append("=" * width)
    for name, cls, shape, n in rows:
        lines.append(f"{name + ' (' + cls + ')':<40}{str(shape):<30}{n:>12,}")
    lines.append("=" * width)
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    lines.append(f"Non-trainable params: {total - trainable:,}")
    lines.append("-" * width)
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
