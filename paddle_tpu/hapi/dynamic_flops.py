"""paddle.flops (reference python/paddle/hapi/dynamic_flops.py): FLOPs
estimate per layer via forward hooks."""

from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu._core.tensor import Tensor

__all__ = ["flops"]


def _shape(x):
    return list(x.shape) if isinstance(x, Tensor) else None


def _count(layer, inputs, output):
    x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
    out = output[0] if isinstance(output, (tuple, list)) else output
    ish, osh = _shape(x), _shape(out)
    if isinstance(layer, nn.Linear):
        return int(np.prod(osh)) * layer.weight.shape[0] * 2
    name = type(layer).__name__
    if name.startswith("Conv"):
        w = layer.weight
        kernel = int(np.prod(w.shape[1:]))
        return int(np.prod(osh)) * kernel * 2
    if "Norm" in name:
        return int(np.prod(ish or [0])) * 7
    if name in ("ReLU", "GELU", "Sigmoid", "Tanh", "Softmax", "SiLU"):
        return int(np.prod(ish or [0]))
    if "Pool" in name:
        return int(np.prod(osh or [0]))
    return 0


def flops(net, input_size, custom_ops=None, print_detail=False):
    total = [0]
    rows = []
    hooks = []
    custom_ops = custom_ops or {}

    def register(layer, prefix=""):
        subs = dict(layer.named_children()) if hasattr(layer, "named_children") else {}
        if not subs:
            def hook(l, inputs, output, prefix=prefix):
                counter = custom_ops.get(type(l))
                n = counter(l, inputs, output) if counter else _count(l, inputs, output)
                total[0] += n
                rows.append((prefix or type(l).__name__, n))

            hooks.append(layer.register_forward_post_hook(hook))
        for name, sub in subs.items():
            register(sub, f"{prefix}.{name}" if prefix else name)

    register(net)
    try:
        x = paddle.zeros(list(input_size))
        from paddle_tpu._core.autograd import no_grad

        was_training = net.training
        net.eval()
        try:
            with no_grad():
                net(x)
        finally:
            if was_training:
                net.train()
    finally:
        for h in hooks:
            h.remove()

    if print_detail:
        for name, n in rows:
            print(f"{name:<50}{n:>16,}")
    print(f"Total GFLOPs: {total[0] / 1e9:.4f}")
    return total[0]
