"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import os
import time

__all__ = [
    "Callback",
    "CallbackList",
    "ProgBarLogger",
    "ModelCheckpoint",
    "EarlyStopping",
    "LRScheduler",
    "ReduceLROnPlateau",
    "VisualDL",
    "WandbCallback",
]


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params or {})

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items() if isinstance(v, float))
            print(f"  step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items() if isinstance(v, float))
            print(f"  epoch {epoch + 1} done in {time.time() - self.t0:.1f}s  {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"epoch_{epoch}")
            self.model.save(path)


def _monitored(logs, monitor):
    """Fetch a monitored metric from eval logs, tolerating the `eval_`
    prefix Model.evaluate puts on its keys (monitor='loss' must match
    'eval_loss', and 'eval_acc' must match whether or not the user wrote
    the prefix).  Returns a float or None."""
    logs = logs or {}
    cur = logs.get(monitor)
    if cur is None:
        cur = logs.get(f"eval_{monitor}")
    if cur is None and monitor.startswith("eval_"):
        cur = logs.get(monitor[len("eval_"):])
    if isinstance(cur, (list, tuple)):
        cur = cur[0] if cur else None
    return cur


def _improved(cur, best, mode, min_delta):
    if best is None:
        return True
    if mode == "min":
        return cur < best - min_delta
    return cur > best + min_delta


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="min", patience=0, min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = baseline
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur, best):
        return _improved(cur, best, self.mode, self.min_delta)

    def on_eval_end(self, logs=None):
        cur = _monitored(logs, self.monitor)
        if cur is None:
            return
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class ReduceLROnPlateau(Callback):
    """Reduce the optimizer LR when a monitored metric stops improving.

    Reference: python/paddle/hapi/callbacks.py ReduceLROnPlateau (keras-
    style callback tier over optimizer.set_lr; distinct from the
    optimizer.lr.ReduceOnPlateau scheduler, which owns the LR inside the
    compiled step).
    """

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="min", min_delta=1e-4, cooldown=0, min_lr=0.0):
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau does not support a factor >= 1.0")
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = mode
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def on_eval_end(self, logs=None):
        cur = _monitored(logs, self.monitor)
        if cur is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if _improved(cur, self.best, self.mode, self.min_delta):
            self.best = cur
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                self._reduce()
                self.cooldown_counter = self.cooldown
                self.wait = 0

    def _reduce(self):
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        old = float(opt.get_lr())
        new = max(old * self.factor, self.min_lr)
        if old - new <= 1e-12:
            return
        try:
            opt.set_lr(new)
        except RuntimeError:
            # an LRScheduler owns the LR — reducing would fight it; warn
            # once instead of crashing fit() mid-training
            import warnings

            warnings.warn(
                "ReduceLROnPlateau: optimizer uses an LRScheduler; "
                "skipping plateau reduction (use the "
                "optimizer.lr.ReduceOnPlateau scheduler instead)")
            self.factor = 1.0  # disables further attempts
            return
        if self.verbose:
            print(f"ReduceLROnPlateau: lr {old:g} -> {new:g}")


class VisualDL(Callback):
    """Scalar logging callback.

    Reference: python/paddle/hapi/callbacks.py VisualDL.  Uses the real
    visualdl LogWriter when the package is importable; otherwise falls
    back to a self-contained JSONL scalar log (one
    {"tag", "step", "value"} per line under `log_dir/scalars.jsonl`) so
    the callback works in hermetic environments — same tags, same
    train/eval split.
    """

    def __init__(self, log_dir="./log"):
        self.log_dir = log_dir
        self.epochs = None
        self._writer = None
        self._jsonl = None
        self._train_step = 0
        self._in_fit = False

    def _ensure_writer(self):
        if self._writer is None and self._jsonl is None:
            os.makedirs(self.log_dir, exist_ok=True)
            try:
                from visualdl import LogWriter  # type: ignore

                self._writer = LogWriter(self.log_dir)
            except ImportError:
                self._jsonl = open(
                    os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def _add_scalar(self, tag, value, step):
        self._ensure_writer()
        try:
            value = float(value)
        except (TypeError, ValueError):
            return
        if self._writer is not None:
            self._writer.add_scalar(tag=tag, value=value, step=step)
        else:
            import json as _json

            self._jsonl.write(_json.dumps(
                {"tag": tag, "step": step, "value": value}) + "\n")
            self._jsonl.flush()

    def _log(self, prefix, logs, step):
        for k, v in (logs or {}).items():
            if k in ("batch_size", "step", "steps"):
                continue
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if v is None:
                continue
            if k.startswith(f"{prefix}_"):  # avoid eval/eval_loss tags
                k = k[len(prefix) + 1:]
            self._add_scalar(f"{prefix}/{k}", v, step)

    def on_train_batch_end(self, step, logs=None):
        self._train_step += 1
        self._log("train", logs, self._train_step)

    def on_train_begin(self, logs=None):
        self._in_fit = True

    def on_eval_end(self, logs=None):
        self._log("eval", logs, self._train_step)
        if not self._in_fit:
            # standalone Model.evaluate(): nothing will call on_train_end,
            # so release the lazily-opened handle here
            self._close()

    def on_train_end(self, logs=None):
        self._in_fit = False
        self._close()

    def _close(self):
        # reset to None so a reused callback instance (second fit(), or a
        # standalone evaluate()) reopens instead of writing to a closed file
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None


class WandbCallback(Callback):
    """Weights & Biases logging (reference: hapi/callbacks.py
    WandbCallback).  Requires the wandb package; raises at construction
    when absent rather than silently dropping metrics."""

    def __init__(self, project=None, job_type="train", **kwargs):
        try:
            import wandb  # type: ignore
        except ImportError as e:
            raise ModuleNotFoundError(
                "WandbCallback requires the wandb package "
                "(pip install wandb)") from e
        self.wandb = wandb
        self.run = wandb.init(project=project, job_type=job_type, **kwargs)
        self._train_step = 0

    def _log(self, prefix, logs, step):
        payload = {}
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)):
                v = v[0] if v else None
            if v is None or k in ("batch_size",):
                continue
            if k.startswith(f"{prefix}_"):  # avoid eval/eval_loss tags
                k = k[len(prefix) + 1:]
            try:
                payload[f"{prefix}/{k}"] = float(v)
            except (TypeError, ValueError):
                continue
        if payload:
            self.run.log(payload, step=step)

    def on_train_batch_end(self, step, logs=None):
        self._train_step += 1
        self._log("train", logs, self._train_step)

    def on_eval_end(self, logs=None):
        self._log("eval", logs, self._train_step)

    def on_train_end(self, logs=None):
        self.run.finish()
