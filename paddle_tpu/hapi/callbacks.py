"""hapi callbacks (reference: python/paddle/hapi/callbacks.py)."""

from __future__ import annotations

import os
import time

__all__ = [
    "Callback",
    "CallbackList",
    "ProgBarLogger",
    "ModelCheckpoint",
    "EarlyStopping",
    "LRScheduler",
]


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params or {})

    def __getattr__(self, name):
        if name.startswith("on_"):
            def fire(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return fire
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items() if isinstance(v, float))
            print(f"  step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = " - ".join(f"{k}: {v:.4f}" for k, v in (logs or {}).items() if isinstance(v, float))
            print(f"  epoch {epoch + 1} done in {time.time() - self.t0:.1f}s  {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"epoch_{epoch}")
            self.model.save(path)


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="min", patience=0, min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = baseline
        self.wait = 0
        self.stopped_epoch = 0

    def _better(self, cur, best):
        if best is None:
            return True
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()
