"""paddle.Model — high-level train/eval/predict loop.

Reference: python/paddle/hapi/model.py:1054 fit, :1756 evaluate/predict.

TPU-native: train_batch compiles the whole imperative step (forward +
backward + optimizer) into one donated-state XLA program via jit.TrainStep;
eval/predict run a jitted forward.  Metrics update on host between steps.
"""

from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor
from paddle_tpu._core.autograd import no_grad

from .callbacks import CallbackList, ProgBarLogger

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._train_step = None

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        from paddle_tpu.jit import TrainStep

        if optimizer is not None and loss is not None:
            def loss_fn(net, *batch):
                *xs, y = batch
                out = net(*xs)
                return self._loss(out, y)

            self._train_step = TrainStep(self.network, optimizer, loss_fn)
        return self

    # ---------------------------------------------------------- single step
    def train_batch(self, inputs, labels=None):
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        batch = [paddle.to_tensor(np.asarray(x)) for x in inputs + labels]
        loss = self._train_step(*batch)
        return [float(loss.item())]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        out = self.network(*[paddle.to_tensor(np.asarray(x)) for x in inputs])
        loss = None
        if self._loss is not None and labels:
            loss = float(self._loss(out, paddle.to_tensor(np.asarray(labels[0]))).item())
        self.network.train()
        return loss, out

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        out = self.network(*[paddle.to_tensor(np.asarray(x)) for x in _to_list(inputs)])
        self.network.train()
        return out

    # ------------------------------------------------------------ main loop
    def _loader(self, data, batch_size, shuffle, drop_last=False):
        from paddle_tpu.io import DataLoader

        if data is None:
            return None
        if hasattr(data, "__iter__") and not hasattr(data, "__getitem__"):
            return data
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1, eval_freq=1,
            log_freq=10, save_dir=None, save_freq=1, verbose=2, drop_last=False,
            shuffle=True, num_workers=0, callbacks=None, accumulate_grad_batches=1, num_iters=None):
        assert self._train_step is not None, "call prepare(optimizer, loss) first"
        loader = self._loader(train_data, batch_size, shuffle, drop_last)
        eval_loader = self._loader(eval_data, batch_size, False)

        cbks = CallbackList(
            (callbacks or []) + ([ProgBarLogger(log_freq, verbose)] if verbose else []),
            model=self,
            params={"epochs": epochs, "steps": len(loader) if hasattr(loader, "__len__") else None},
        )
        self.stop_training = False
        cbks.on_train_begin()
        history = {"loss": []}
        it = 0
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            epoch_losses = []
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                batch = batch if isinstance(batch, (list, tuple)) else [batch]
                xs, ys = batch[:-1], batch[-1:]
                (lv,) = self.train_batch(xs, ys)
                epoch_losses.append(lv)
                cbks.on_train_batch_end(step, {"loss": lv})
                it += 1
                if num_iters is not None and it >= num_iters:
                    self.stop_training = True
                    break
            logs = {"loss": float(np.mean(epoch_losses))} if epoch_losses else {}
            history["loss"].append(logs.get("loss"))
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, batch_size=batch_size, verbose=0, callbacks=cbks)
                logs.update(eval_logs)
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
            if self.stop_training:
                break
        cbks.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0, callbacks=None, num_samples=None):
        loader = self._loader(eval_data, batch_size, False)
        cbks = callbacks if isinstance(callbacks, CallbackList) else CallbackList(_to_list(callbacks), model=self)
        cbks.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            xs, ys = batch[:-1], batch[-1:]
            loss, out = self.eval_batch(xs, ys)
            if loss is not None:
                losses.append(loss)
            for m in self._metrics:
                label = paddle.to_tensor(np.asarray(ys[0])) if ys else None
                m.update(*[x for x in _to_list(m.compute(out, label))])
        logs = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs[f"eval_{m.name()}" if isinstance(m.name(), str) else "eval_metric"] = m.accumulate()
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False, verbose=1, callbacks=None):
        loader = self._loader(test_data, batch_size, False)
        outs = []
        for batch in loader:
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            if self._loss is not None and len(batch) > 1:
                batch = batch[:-1]  # dataset yields (inputs..., label); drop label
            out = self.predict_batch(batch)
            outs.append(np.asarray(out._value) if isinstance(out, Tensor) else out)
        if stack_outputs and outs:
            return [np.concatenate(outs, axis=0)]
        return outs

    # --------------------------------------------------------------- state
    def save(self, path, training=True):
        """training=True: checkpoint (params + optimizer state).
        training=False: INFERENCE export via jit.save — the deployable
        .pdmodel/.pdiparams artifact loadable by inference.Predictor
        (reference hapi/model.py Model.save(training=False) contract);
        requires the Model to have been constructed with inputs=
        InputSpec list."""
        if not training:
            if not self._inputs:
                raise ValueError(
                    "Model.save(training=False) exports an inference "
                    "artifact and needs the Model's inputs= InputSpec list")
            import paddle_tpu.jit as jit

            specs = self._inputs if isinstance(self._inputs, (list, tuple)) \
                else [self._inputs]
            jit.save(self.network, path, input_spec=list(specs))
            return
        state = {"model": dict(self.network.state_dict())}
        if self._optimizer is not None:
            state["opt"] = self._optimizer.state_dict()
        paddle.save(state, path + ".pdparams")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = paddle.load(path + ".pdparams")
        self.network.set_state_dict(state["model"])
        if not reset_optimizer and self._optimizer is not None and "opt" in state:
            self._optimizer.set_state_dict(state["opt"])

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)
