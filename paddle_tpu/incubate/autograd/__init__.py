"""paddle.incubate.autograd — functional/forward-mode autodiff surface.

Reference: python/paddle/incubate/autograd/__init__.py (jvp/vjp/Jacobian/
Hessian from functional.py, prim-mode toggles from primx.py).

TPU-native: jax's jvp/vjp ARE the primitive-level autodiff the reference
builds its prim flag machinery for — enable_prim/disable_prim exist for
script compat and report prim always-on (every grad here is computed on the
primitive jaxpr).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from paddle_tpu.autograd.functional import hessian as Hessian  # noqa: N812
from paddle_tpu.autograd.functional import jacobian as Jacobian  # noqa: N812

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "enable_prim", "disable_prim", "prim_enabled", "forward_grad", "grad"]


def _unwrap(ts):
    if isinstance(ts, (list, tuple)):
        return [t._value if isinstance(t, Tensor) else jnp.asarray(t) for t in ts]
    return [ts._value if isinstance(ts, Tensor) else jnp.asarray(ts)]


def jvp(func, xs, v=None):
    """Forward-mode JVP (reference: incubate/autograd/functional.py jvp):
    returns (func(xs), J @ v)."""
    xv = _unwrap(xs)
    tv = _unwrap(v) if v is not None else [jnp.ones_like(x) for x in xv]

    def f(*args):
        out = func(*[Tensor(a) for a in args])
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [o._value if isinstance(o, Tensor) else jnp.asarray(o) for o in outs]

    primals, tangents = jax.jvp(f, tuple(xv), tuple(tv))
    return [Tensor(p) for p in primals], [Tensor(t) for t in tangents]


def vjp(func, xs, v=None):
    """Reverse-mode VJP (reference functional.py vjp): (func(xs), v @ J)."""
    xv = _unwrap(xs)

    def f(*args):
        out = func(*[Tensor(a) for a in args])
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [o._value if isinstance(o, Tensor) else jnp.asarray(o) for o in outs]

    primals, pullback = jax.vjp(f, *xv)
    cots = _unwrap(v) if v is not None else [jnp.ones_like(p) for p in primals]
    grads = pullback(list(cots))
    return [Tensor(p) for p in primals], [Tensor(g) for g in grads]


def forward_grad(func, xs, v=None):
    """Alias of jvp's tangent output (reference primx forward_grad)."""
    _, tangents = jvp(func, xs, v)
    return tangents


def grad(func, xs, v=None):
    """Primitive-mode grad (reference incubate.autograd.grad)."""
    _, grads = vjp(func, xs, v)
    return grads


_prim = {"enabled": True}


def enable_prim():
    _prim["enabled"] = True


def disable_prim():
    # autodiff on jaxprs cannot be turned off; record intent for compat
    _prim["enabled"] = False


def prim_enabled():
    return _prim["enabled"]
