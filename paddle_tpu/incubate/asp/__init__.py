"""Automatic SParsity — n:m structured sparsity workflow (reference:
python/paddle/incubate/asp/asp.py — prune_model, decorate,
set_excluded_layers, ASPHelper; mask algorithms in supported_layer_list.py
/ utils.py mask_1d/mask_2d_greedy/mask_2d_best).

TPU-first: masks are computed with vectorized jnp top-k over n:m groups
(no per-element python), stored per parameter, and re-applied after each
optimizer step by the decorated optimizer — the same "prune, then keep
pruned through training" workflow the reference runs for 2:4 sparse tensor
cores; on TPU the win is memory/bandwidth rather than sparse MMA."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = [
    "prune_model", "decorate", "set_excluded_layers", "reset_excluded_layers",
    "calculate_density", "check_sparsity", "create_mask", "ASPHelper",
]


class ASPHelper:
    """reference asp.py:515."""

    _excluded = set()
    _masks = {}  # param name -> jnp mask

    @classmethod
    def reset(cls):
        cls._excluded = set()
        cls._masks = {}


def set_excluded_layers(param_names, main_program=None):
    """reference asp.py:40."""
    ASPHelper._excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    """reference asp.py:127."""
    ASPHelper._excluded = set()


def calculate_density(x):
    """reference utils.py calculate_density."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _mask_1d(w, n, m):
    groups = w.reshape(w.shape[:-1] + (w.shape[-1] // m, m))
    scores = jnp.abs(groups)
    order = jnp.argsort(scores, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    mask = (ranks >= m - n).astype(w.dtype)
    return mask.reshape(w.shape)


def _mask_2d_greedy(w, n, m):
    """n:m along BOTH the last two dims per m x m tile (reference
    utils.py mask_2d_greedy): greedily keep the largest entries subject to
    per-row and per-column n-of-m budgets inside each tile."""
    if w.ndim < 2 or w.shape[-1] % m or w.shape[-2] % m:
        return jnp.ones_like(w)
    rows, cols = w.shape[-2], w.shape[-1]
    lead = w.shape[:-2]
    tiles = w.reshape(lead + (rows // m, m, cols // m, m))
    tiles = jnp.moveaxis(tiles, -2, -3)  # [..., R, C, m, m]
    flat = np.asarray(tiles).reshape(-1, m, m)
    out = np.zeros_like(flat)
    for t in range(flat.shape[0]):
        tile = np.abs(flat[t])
        row_budget = np.full(m, n)
        col_budget = np.full(m, n)
        for idx in np.argsort(-tile, axis=None):
            r, c = divmod(int(idx), m)
            if row_budget[r] > 0 and col_budget[c] > 0:
                out[t, r, c] = 1
                row_budget[r] -= 1
                col_budget[c] -= 1
    mask = out.reshape(lead + (rows // m, cols // m, m, m))
    mask = np.moveaxis(mask, -3, -2).reshape(w.shape)
    return jnp.asarray(mask, w.dtype)


_MASK_ALGOS = {"mask_1d": _mask_1d, "mask_2d_greedy": _mask_2d_greedy,
               "mask_2d_best": _mask_2d_greedy}


def create_mask(tensor, func_name="mask_1d", n=2, m=4):
    """n:m mask (reference utils.py create_mask; mask_1d keeps the n
    largest-|w| per group of m along the last axis; mask_2d_* constrain
    both dims per tile — mask_2d_best currently shares the greedy
    implementation)."""
    w = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    if func_name not in _MASK_ALGOS:
        raise ValueError(f"unknown mask algorithm {func_name!r}; one of {sorted(_MASK_ALGOS)}")
    if w.ndim < 1 or w.shape[-1] % m != 0:
        return Tensor(jnp.ones_like(w))
    return Tensor(_MASK_ALGOS[func_name](w, n, m))


def check_sparsity(mask, n=2, m=4):
    """True if every m-group has at most (m-n) zeros' complement — i.e.,
    exactly <=n nonzeros (reference utils.py check_mask_1d)."""
    arr = np.asarray(mask._value if isinstance(mask, Tensor) else mask)
    if arr.shape[-1] % m != 0:
        return False
    groups = arr.reshape(-1, m)
    return bool((np.count_nonzero(groups, axis=1) <= n).all())


def _prunable(name, param):
    if name in ASPHelper._excluded:
        return False
    shape = param.shape
    return len(shape) >= 2 and shape[-1] % 4 == 0


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every prunable weight (reference asp.py:302)."""
    masks = {}
    for name, param in model.named_parameters():
        if not _prunable(name, param):
            continue
        mask = create_mask(param, mask_algo, n, m)
        param._bind(param._value * mask._value)
        if with_mask:
            masks[name] = (param, mask._value)
    ASPHelper._masks.update(masks)  # merge: earlier models keep their masks
    return {name: m for name, (_, m) in masks.items()}


class OptimizerWithSparsityGuarantee:
    """reference asp.py:216 decorate() wrapper: re-applies masks after each
    step so pruned weights stay zero through training."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        self._reapply()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        # the inner optimizer's minimize calls its own step(), which would
        # bypass this wrapper — re-apply masks after it returns
        out = self._optimizer.minimize(loss, startup_program, parameters, no_grad_set)
        self._reapply()
        return out

    def _reapply(self):
        for _name, (p, mask) in ASPHelper._masks.items():
            p._bind(p._value * mask)


def decorate(optimizer):
    """reference asp.py:216."""
    return OptimizerWithSparsityGuarantee(optimizer)
