"""paddle.incubate.distributed parity (models.moe lands here)."""

from . import models  # noqa: F401
