"""MoELayer — expert-parallel mixture of experts.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:263
(global_scatter/global_gather all-to-all dispatch at :119-190).

TPU-native dataflow (all static shapes, single compiled program):
  1. gate → combine_weights [T, E, C], dispatch_mask [T, E, C]  (fixed capacity)
  2. dispatch einsum  [T,E,C] x [T,d] → [E, C, d]
  3. EP all-to-all over the 'ep' mesh axis: [E=w*le, C, d] → [le, w*C, d]
     (each rank receives every rank's tokens for its local experts)
  4. local experts applied to their [w*C, d] slab (static Python loop)
  5. reverse all-to-all, combine einsum → [T, d]

Under expert parallelism the layer must run inside an SPMD region (shard_map
with a collective_axis_scope exposing the EP axis) — the fleet engines set
this up; at world 1 the all-to-alls are identity.
"""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.tensor._ops_common import apply

import jax.numpy as jnp
from jax import lax

from paddle_tpu.distributed.communication.ops import _axis_for

from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate


class MoELayer(nn.Layer):
    """MoELayer(d_model, experts, gate="gshard", moe_group=None, top_k=2).

    `experts`: LayerList (or list) of expert Layers living on this rank
    (len = num_local_experts); total experts = len(experts) * ep_world.
    """

    def __init__(
        self,
        d_model,
        experts,
        gate="gshard",
        moe_group=None,
        top_k=2,
        capacity_factor=2.0,
        recompute_interval=0,
    ):
        super().__init__()
        self.d_model = d_model
        self.experts = nn.LayerList(experts) if not isinstance(experts, nn.LayerList) else experts
        self.moe_group = moe_group
        self.ep_world = moe_group.nranks if moe_group is not None else 1
        self.num_local_experts = len(self.experts)
        self.num_experts = self.num_local_experts * self.ep_world

        if isinstance(gate, BaseGate):
            self.gate = gate
        elif gate in ("gshard", None):
            self.gate = GShardGate(d_model, self.num_experts, capacity_factor=capacity_factor)
        elif gate == "naive":
            self.gate = NaiveGate(d_model, self.num_experts, top_k=top_k, capacity_factor=capacity_factor)
        elif gate == "switch":
            self.gate = SwitchGate(d_model, self.num_experts, capacity_factor=capacity_factor)
        else:
            raise ValueError(f"unknown gate {gate!r}")
        self.aux_loss = None

    def _a2a(self, x, name):
        if self.moe_group is None or self.ep_world == 1:
            return x
        ax = _axis_for(self.moe_group)
        if isinstance(ax, tuple):  # group=None world tuple never applies here
            ax = None
        if ax is None:
            raise RuntimeError(
                "MoELayer has an EP group of size "
                f"{self.ep_world} but no matching mesh axis is in scope; "
                "run the layer inside the distributed step "
                "(collective_axis_scope exposing the EP axis)"
            )
        return apply(name, lambda v: lax.all_to_all(v, ax, split_axis=0, concat_axis=0, tiled=True), x)

    def forward(self, x):
        orig_shape = x.shape
        x2d = x.reshape([-1, self.d_model])

        combine, dispatch, aux = self.gate.dispatch(x2d)
        self.aux_loss = aux

        # [T, E, C] x [T, d] -> [E, C, d]
        dispatched = paddle.einsum("tec,td->ecd", dispatch.astype(x2d.dtype), x2d)

        w, le = self.ep_world, self.num_local_experts
        cap = dispatched.shape[-2]
        # EP exchange: [w*le, C, d] -> rows regrouped so that this rank holds
        # [w, le, C, d] from every source rank for its local experts.
        dispatched = self._a2a(dispatched.reshape([w * le * cap, self.d_model]), "moe_scatter")
        expert_in = dispatched.reshape([w, le, cap, self.d_model])

        outs = []
        for i, expert in enumerate(self.experts):
            slab = expert_in[:, i].reshape([w * cap, self.d_model])
            outs.append(expert(slab).reshape([w, 1, cap, self.d_model]))
        expert_out = paddle.concat(outs, axis=1)  # [w, le, C, d]

        gathered = self._a2a(expert_out.reshape([w * le * cap, self.d_model]), "moe_gather")
        gathered = gathered.reshape([self.num_experts, cap, self.d_model])

        out = paddle.einsum("tec,ecd->td", combine.astype(x2d.dtype), gathered)
        return out.reshape(orig_shape)
