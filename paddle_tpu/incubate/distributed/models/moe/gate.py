"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
{naive_gate,gshard_gate,switch_gate}.py).

TPU-first contract: every gate returns **fixed-shape** tensors —
(combine_weights [T, E, C], dispatch_mask [T, E, C], aux_loss scalar) — so the
dispatch/combine einsums and the EP all-to-all compile to static XLA programs
(no variable token counts; overflow tokens are dropped by capacity, matching
GShard semantics).
"""

from __future__ import annotations

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.tensor._ops_common import apply

import jax
import jax.numpy as jnp


def _capacity(num_tokens, num_experts, capacity_factor, top_k):
    cap = int(capacity_factor * top_k * ((num_tokens + num_experts - 1) // num_experts))
    return max(cap, 4)


def _topk_dispatch(logits, top_k, capacity, *, jitter_eps=0.0, compute_aux=True, key=None):
    """Shared fixed-capacity dispatch math (pure jax).

    logits: [T, E].  Returns combine [T, E, C] f32, dispatch bool [T, E, C],
    aux loss (load-balancing, GShard eq.4), all static shapes.
    """
    t, e = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # iterate top_k choices; positions assigned by prefix-sum per expert
    expert_prior = jnp.zeros((e,), jnp.int32)
    total_combine = jnp.zeros((t, e, capacity), jnp.float32)
    denom = jnp.zeros((t, 1), jnp.float32)
    aux = jnp.float32(0.0)

    masked = gates
    for k in range(top_k):
        idx = jnp.argmax(masked, axis=-1)  # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [T, E]
        if k == 0 and compute_aux:
            # GShard load-balance loss: E * sum_e mean_t(gate_e) * mean_t(is_top1_e)
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(onehot, axis=0)
            aux = jnp.sum(me * ce) * e
        # position of each token within its chosen expert (+ tokens routed in
        # earlier k rounds)
        pos = jnp.cumsum(onehot, axis=0) - 1.0 + expert_prior[None, :].astype(jnp.float32)
        pos_tok = jnp.sum(pos * onehot, axis=-1)  # [T]
        keep = pos_tok < capacity
        gate_val = jnp.sum(gates * onehot, axis=-1) * keep  # [T]
        pos_clip = jnp.clip(pos_tok, 0, capacity - 1).astype(jnp.int32)
        cap_onehot = jax.nn.one_hot(pos_clip, capacity, dtype=jnp.float32)  # [T, C]
        total_combine = total_combine + (gate_val[:, None] * onehot)[:, :, None] * cap_onehot[:, None, :]
        denom = denom + gate_val[:, None]
        expert_prior = expert_prior + jnp.sum(onehot, axis=0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)

    denom = jnp.where(denom == 0.0, 1.0, denom)
    total_combine = total_combine / denom[:, :, None]
    dispatch = total_combine > 0.0
    return total_combine, dispatch, aux


class BaseGate(nn.Layer):
    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=1.25):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.linear = nn.Linear(d_model, num_experts, bias_attr=False)
        self._loss = None

    def get_loss(self, clear=True):
        loss = self._loss
        if clear:
            self._loss = None
        return loss

    def dispatch(self, x, capacity=None, compute_aux=True):
        logits = self.linear(x)  # [T, E]
        t = x.shape[0]
        cap = capacity or _capacity(t, self.num_experts, self.capacity_factor, self.top_k)

        out = apply(
            "moe_gate_dispatch",
            lambda lg: _topk_dispatch(lg, self.top_k, cap, compute_aux=compute_aux),
            logits,
        )
        combine, dispatch, aux = out
        self._loss = aux
        return combine, dispatch, aux


class NaiveGate(BaseGate):
    """Top-k softmax gate, no aux loss (reference naive_gate.py)."""

    def dispatch(self, x, capacity=None, compute_aux=False):
        return super().dispatch(x, capacity, compute_aux=False)


class GShardGate(BaseGate):
    """Top-2 gate with GShard load-balancing aux loss (reference gshard_gate.py)."""

    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=2.0, group=None):
        super().__init__(d_model, num_experts, top_k=2, capacity_factor=capacity_factor)


class SwitchGate(BaseGate):
    """Top-1 switch gate (reference switch_gate.py)."""

    def __init__(self, d_model, num_experts, top_k=1, capacity_factor=1.25, group=None):
        super().__init__(d_model, num_experts, top_k=1, capacity_factor=capacity_factor)
