"""paddle.incubate.nn.functional parity (reference:
python/paddle/incubate/nn/functional/): fused ops backed by the Pallas
kernel library (paddle_tpu.ops) on TPU, jnp references elsewhere.

All entry points take/return paddle_tpu Tensors and record on the autograd
tape; the underlying jax fns carry custom VJPs so backward also runs the
fused kernels.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from paddle_tpu import ops as _ops
from paddle_tpu._core import random as _random
from paddle_tpu.tensor._ops_common import apply, ensure_tensor

__all__ = [
    "fused_rms_norm",
    "fused_layer_norm",
    "fused_rotary_position_embedding",
    "fused_matmul_bias",
    "fused_linear",
    "fused_linear_activation",
    "fused_dropout_add",
    "swiglu",
    "fused_bias_act",
    "masked_multihead_attention",
    "block_multihead_attention",
    "fused_ec_moe",
    "variable_length_memory_efficient_attention",
    "fused_dot_product_attention",
    "fused_gate_attention",
]


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1, residual=None):
    """Reference: python/paddle/incubate/nn/functional/fused_rms_norm.py.
    norm_bias is accepted for signature parity (RMSNorm has no bias; applied
    additively post-scale when given)."""
    x = ensure_tensor(x)
    norm_weight = ensure_tensor(norm_weight)
    extras = []
    if norm_bias is not None:
        extras.append(ensure_tensor(norm_bias))
    if residual is not None:
        extras.append(ensure_tensor(residual))

    def _fn(xv, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if norm_bias is not None else None
        res = rest.pop(0) if residual is not None else None
        out = _ops.fused_rms_norm(xv, wv, epsilon=epsilon, residual=res)
        if res is not None:
            out, pre = out
            if bv is not None:
                out = out + bv
            return out, pre
        if bv is not None:
            out = out + bv
        return out

    return apply("fused_rms_norm", _fn, x, norm_weight, *extras)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, begin_norm_axis=-1, residual=None):
    x = ensure_tensor(x)
    norm_weight = ensure_tensor(norm_weight)
    args = [x, norm_weight]
    if norm_bias is not None:
        args.append(ensure_tensor(norm_bias))
    if residual is not None:
        args.append(ensure_tensor(residual))

    def _fn(xv, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if norm_bias is not None else None
        res = rest.pop(0) if residual is not None else None
        return _ops.fused_layer_norm(xv, wv, bv, epsilon=epsilon, residual=res)

    return apply("fused_layer_norm", _fn, *args)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None, position_ids=None, use_neox_rotary_style=True, time_major=False, rotary_emb_base=10000.0):
    """Reference: python/paddle/incubate/nn/functional/fused_rotary_position_embedding.py.

    Here sin/cos are [S, H/2] tables (built by the model); interleaved-pair
    ("GPT-NeoX style" pairs) rotation via the Pallas kernel.
    """
    q = ensure_tensor(q)
    args = [q]
    if k is not None:
        args.append(ensure_tensor(k))
    cos_t = ensure_tensor(cos)
    sin_t = ensure_tensor(sin)
    args += [cos_t, sin_t]
    if position_ids is not None:
        args.append(ensure_tensor(position_ids))

    def _fn(qv, *rest):
        rest = list(rest)
        kv = rest.pop(0) if k is not None else None
        cv, sv = rest[0], rest[1]
        pids = rest[2] if len(rest) > 2 else None
        return _ops.fused_rotary_position_embedding(qv, kv, None, cos=cv, sin=sv, position_ids=pids)

    out = apply("fused_rope", _fn, *args)
    if k is not None and v is not None:
        return out[0], out[1], ensure_tensor(v)
    return out


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False):
    """matmul+bias in one op — XLA fuses the epilogue into the MXU matmul, so
    the jnp form IS the fused kernel on TPU (reference: fused_gemm_epilogue)."""
    x, y = ensure_tensor(x), ensure_tensor(y)
    extras = [ensure_tensor(bias)] if bias is not None else []

    def _fn(xv, yv, *rest):
        if transpose_x:
            xv = jnp.swapaxes(xv, -1, -2)
        if transpose_y:
            yv = jnp.swapaxes(yv, -1, -2)
        out = jnp.matmul(xv, yv)
        if rest:
            out = out + rest[0]
        return out

    return apply("fused_matmul_bias", _fn, x, y, *extras)


def fused_linear(x, weight, bias=None, transpose_weight=False):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False, activation="gelu"):
    out = fused_matmul_bias(x, y, bias, trans_x, trans_y)

    def _act(v):
        if activation == "gelu":
            return jax.nn.gelu(v)
        if activation == "relu":
            return jnp.maximum(v, 0)
        if activation in ("none", ""):
            return v
        raise ValueError(f"unsupported activation {activation}")

    return apply("fused_linear_activation", _act, out)


def fused_bias_act(x, bias=None, act_method="gelu"):
    x = ensure_tensor(x)
    extras = [ensure_tensor(bias)] if bias is not None else []

    def _fn(xv, *rest):
        if rest:
            xv = xv + rest[0]
        if act_method == "gelu":
            return jax.nn.gelu(xv)
        if act_method == "relu":
            return jnp.maximum(xv, 0)
        if act_method in ("swiglu",):
            a, b = jnp.split(xv, 2, axis=-1)
            return _ops.swiglu(a, b)
        raise ValueError(f"unsupported act {act_method}")

    return apply("fused_bias_act", _fn, x, *extras)


def swiglu(x, y=None):
    x = ensure_tensor(x)
    extras = [ensure_tensor(y)] if y is not None else []

    def _fn(xv, *rest):
        return _ops.swiglu(xv, rest[0] if rest else None)

    return apply("swiglu", _fn, x, *extras)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", seed=None):
    """dropout(x) + y fused (reference fused_dropout_add kernel)."""
    from paddle_tpu.nn.functional.common import dropout

    out = dropout(ensure_tensor(x), p, training=training, mode=mode)
    return out + ensure_tensor(y)


def masked_multihead_attention(x, cache_kv, *, num_heads, head_dim, seq_lens=None, rotary_tables=None, position_offset=0):
    """Single-token decode attention against a KV cache (reference:
    paddle.incubate.nn.functional.masked_multihead_attention,
    paddle/phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu).

    x: [B, 3*N*H] fused qkv for the new token; cache_kv: [2, B, N, S_max, H].
    Returns (out [B, N*H], updated cache).  Decode attention is
    bandwidth-bound: XLA's gather+matmul on a [S_max, H] cache block is
    already near roofline, so the jnp form is the TPU kernel.
    """
    x = ensure_tensor(x)
    cache_kv = ensure_tensor(cache_kv)

    def _fn(xv, cache):
        b = xv.shape[0]
        qkv = xv.reshape(b, 3, num_heads, head_dim)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [B, N, H]
        if rotary_tables is not None:
            cos, sin = rotary_tables
            c = jax.lax.dynamic_slice_in_dim(jnp.asarray(cos), position_offset, 1, 0)[0]
            s = jax.lax.dynamic_slice_in_dim(jnp.asarray(sin), position_offset, 1, 0)[0]

            def rot(t):
                t2 = t.reshape(b, num_heads, head_dim // 2, 2)
                r1 = t2[..., 0] * c - t2[..., 1] * s
                r2 = t2[..., 1] * c + t2[..., 0] * s
                return jnp.stack([r1, r2], -1).reshape(b, num_heads, head_dim)

            q, k = rot(q), rot(k)
        cache = jax.lax.dynamic_update_slice(
            cache, jnp.stack([k, v])[:, :, :, None, :], (0, 0, 0, position_offset, 0)
        )
        keys = cache[0]  # [B, N, S_max, H]
        vals = cache[1]
        scale = 1.0 / math.sqrt(head_dim)
        logits = jnp.einsum("bnh,bnsh->bns", q.astype(jnp.float32), keys.astype(jnp.float32)) * scale
        span = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2)
        valid = span <= position_offset
        logits = jnp.where(valid, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bns,bnsh->bnh", probs, vals.astype(jnp.float32))
        return out.reshape(b, num_heads * head_dim).astype(xv.dtype), cache

    return apply("masked_multihead_attention", _fn, x, cache_kv)


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None, kv_seq_lens=None, mask=None, scale=None, causal=False):
    """Reference: python/paddle/incubate/nn/functional/variable_length_memory_efficient_attention.py.
    q/k/v: [B, N, S, H].  Variable lengths become an additive mask; the fused
    path is the flash kernel when lengths are uniform."""
    query, key, value = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    extras = []
    if mask is not None:
        extras.append(ensure_tensor(mask))

    def _fn(q, k, v, *rest):
        m = rest[0] if rest else None
        sc = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
        logits = jnp.einsum("bnqh,bnkh->bnqk", q.astype(jnp.float32), k.astype(jnp.float32)) * sc
        if causal:
            ql, kl = logits.shape[-2], logits.shape[-1]
            cm = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
            logits = jnp.where(cm, logits, -1e30)
        if seq_lens is not None:
            kpos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3)
            lens = jnp.asarray(kv_seq_lens if kv_seq_lens is not None else seq_lens).reshape(-1, 1, 1, 1)
            logits = jnp.where(kpos < lens, logits, -1e30)
        if m is not None:
            logits = logits + m
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bnqk,bnkh->bnqh", probs, v.astype(jnp.float32)).astype(q.dtype)

    return apply("variable_length_memory_efficient_attention", _fn, query, key, value, *extras)


def block_multihead_attention(
    qkv,
    key_cache,
    value_cache,
    block_tables,
    seq_lens,
    *,
    num_heads,
    num_kv_heads=None,
    head_dim,
    rotary_tables=None,
    scale=None,
):
    """Paged-KV decode attention (reference:
    python/paddle/incubate/nn/functional/block_multihead_attention.py,
    kernel paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu).

    One decode token per sequence.  qkv: [B, (N+2*Nkv)*H] fused projection;
    key_cache/value_cache: [num_blocks, Nkv, block_size, H] paged pools;
    block_tables: [B, max_blocks]; seq_lens: [B] length INCLUDING this token.
    Returns (out [B, N*H], key_cache', value_cache').  The reference's
    encoder/decoder seq-len bookkeeping collapses: prefill runs through the
    normal flash path, only decode is paged (see models/llama.py generate).
    """
    from paddle_tpu.ops import paged_attention as pa

    qkv = ensure_tensor(qkv)
    key_cache = ensure_tensor(key_cache)
    value_cache = ensure_tensor(value_cache)
    block_tables = ensure_tensor(block_tables)
    seq_lens = ensure_tensor(seq_lens)
    nkv = num_kv_heads or num_heads

    def _fn(qkv_v, kc, vc, bt, lens):
        b = qkv_v.shape[0]
        splits = [num_heads * head_dim, nkv * head_dim, nkv * head_dim]
        q = qkv_v[:, : splits[0]].reshape(b, num_heads, head_dim)
        k = qkv_v[:, splits[0] : splits[0] + splits[1]].reshape(b, nkv, head_dim)
        v = qkv_v[:, splits[0] + splits[1] :].reshape(b, nkv, head_dim)
        pos = lens - 1  # slot of this token
        if rotary_tables is not None:
            cos, sin = rotary_tables
            q = pa.rope_rotate_by_position(q, cos, sin, pos)
            k = pa.rope_rotate_by_position(k, cos, sin, pos)
        kc = pa.paged_write(kc, k, bt, pos)
        vc = pa.paged_write(vc, v, bt, pos)
        out = pa.paged_decode_attention(q, kc, vc, bt, lens, scale=scale)
        return out.reshape(b, num_heads * head_dim), kc, vc

    return apply("block_multihead_attention", _fn, qkv, key_cache, value_cache, block_tables, seq_lens)


def fused_ec_moe(x, gate_weight, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias, act_type="gelu"):
    """Fused expert-computation MoE (reference:
    python/paddle/incubate/nn/functional/fused_ec_moe.py, CUDA kernel
    fused_ec_moe under phi/kernels/fusion): every token runs EVERY expert's
    FFN via batched matmuls and the outputs are mixed by softmax gate
    weights.  On TPU the two einsums land directly on the MXU with the gate
    mix fused by XLA — the dense-MoE tier used for small expert counts
    (capacity-dispatch MoE lives in incubate.distributed MoELayer)."""
    x = ensure_tensor(x)
    args = [x, ensure_tensor(gate_weight), ensure_tensor(bmm0_weight), ensure_tensor(bmm0_bias),
            ensure_tensor(bmm1_weight), ensure_tensor(bmm1_bias)]

    def _fn(xv, gw, w0, b0, w1, b1):
        # xv: [B, S, D]; gate per the reference contract is per-token LOGITS
        # [B, S, E]; a [D, E] projection weight is also accepted (then the
        # logits are x @ gw).  Biases may be [E, F] or the reference's
        # [E, 1, F].
        if gw.ndim == 3:
            logits = gw.astype(jnp.float32)
        else:
            logits = xv.astype(jnp.float32) @ gw.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        b0 = b0.reshape(b0.shape[0], -1)
        b1 = b1.reshape(b1.shape[0], -1)
        h = jnp.einsum("bsd,edf->bsef", xv, w0) + b0[None, None]
        if act_type == "gelu":
            h = jax.nn.gelu(h)
        elif act_type == "relu":
            h = jnp.maximum(h, 0)
        else:
            raise ValueError(f"unsupported act {act_type}")
        eo = jnp.einsum("bsef,efd->bsed", h, w1) + b1[None, None]
        return jnp.einsum("bsed,bse->bsd", eo.astype(jnp.float32), probs).astype(xv.dtype)

    return apply("fused_ec_moe", _fn, *args)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False, pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None, cache_kv=None, attn_mask=None, dropout_rate=0.0, attn_dropout_rate=0.0, ln_epsilon=1e-5, training=True, mode="upscale_in_train", ring_id=-1, add_residual=True, num_heads=-1, transpose_qkv_wb=False, name=None):
    """One-call MHA block (reference:
    python/paddle/incubate/nn/functional/fused_transformer.py
    fused_multi_head_attention): [pre-LN] -> qkv matmul -> attention ->
    out proj -> [residual add] -> [post-LN].  XLA fuses the epilogues; the
    attention core is scaled_dot_product_attention (flash kernel on TPU)."""
    import paddle_tpu.nn.functional as NF
    from paddle_tpu.tensor import linalg as L
    from paddle_tpu.tensor import manipulation as M
    from paddle_tpu.tensor import math as TM

    x = ensure_tensor(x)
    residual = x
    if pre_layer_norm and pre_ln_scale is not None:
        x = NF.layer_norm(x, x.shape[-1:], weight=pre_ln_scale, bias=pre_ln_bias, epsilon=pre_ln_epsilon)
    qkvw = ensure_tensor(qkv_weight)
    B, S, E = x.shape
    if transpose_qkv_wb:
        # weight [E, 3*E]
        if num_heads <= 0:
            raise ValueError("transpose_qkv_wb=True requires num_heads > 0")
        qkv = L.matmul(x, qkvw)
        nh = num_heads
        hd = E // nh
        qkv = M.reshape(qkv, [B, S, 3, nh, hd])
    else:
        # weight [3, n_heads, head_dim, E]
        nh, hd = qkvw.shape[1], qkvw.shape[2]
        w2 = M.reshape(qkvw, [3 * nh * hd, E])
        qkv = L.matmul(x, M.transpose(w2, [1, 0]))
        qkv = M.reshape(qkv, [B, S, 3, nh, hd])
    if qkv_bias is not None:
        qkv = TM.add(qkv, M.reshape(ensure_tensor(qkv_bias), [1, 1, 3, nh, hd]))
    q = M.squeeze(M.slice(qkv, [2], [0], [1]), [2])
    k = M.squeeze(M.slice(qkv, [2], [1], [2]), [2])
    v = M.squeeze(M.slice(qkv, [2], [2], [3]), [2])
    out = NF.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate if training else 0.0, is_causal=False, training=training)
    out = M.reshape(out, [B, S, nh * hd])
    out = L.matmul(out, ensure_tensor(linear_weight))
    if linear_bias is not None:
        out = TM.add(out, ensure_tensor(linear_bias))
    if dropout_rate:
        out = NF.dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = TM.add(residual, out)
    if not pre_layer_norm and ln_scale is not None:
        out = NF.layer_norm(out, out.shape[-1:], weight=ln_scale, bias=ln_bias, epsilon=ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None, linear2_bias=None, ln1_scale=None, ln1_bias=None, ln2_scale=None, ln2_bias=None, dropout1_rate=0.5, dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5, ln2_epsilon=1e-5, pre_layer_norm=False, training=True, mode="upscale_in_train", ring_id=-1, add_residual=True, name=None):
    """Reference: fused_feedforward — [pre-LN] -> linear1 -> act -> dropout ->
    linear2 -> dropout -> residual -> [post-LN]."""
    import paddle_tpu.nn.functional as NF
    from paddle_tpu.tensor import linalg as L
    from paddle_tpu.tensor import math as TM

    x = ensure_tensor(x)
    residual = x
    if pre_layer_norm and ln1_scale is not None:
        x = NF.layer_norm(x, x.shape[-1:], weight=ln1_scale, bias=ln1_bias, epsilon=ln1_epsilon)
    h = L.matmul(x, ensure_tensor(linear1_weight))
    if linear1_bias is not None:
        h = TM.add(h, ensure_tensor(linear1_bias))
    h = getattr(NF, activation)(h)
    if dropout1_rate:
        h = NF.dropout(h, dropout1_rate, training=training, mode=mode)
    h = L.matmul(h, ensure_tensor(linear2_weight))
    if linear2_bias is not None:
        h = TM.add(h, ensure_tensor(linear2_bias))
    if dropout2_rate:
        h = NF.dropout(h, dropout2_rate, training=training, mode=mode)
    if add_residual:
        h = TM.add(residual, h)
    if not pre_layer_norm and ln2_scale is not None:
        h = NF.layer_norm(h, h.shape[-1:], weight=ln2_scale, bias=ln2_bias, epsilon=ln2_epsilon)
    return h


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None, ln_scale=None, ln_bias=None, dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode="upscale_in_train", name=None):
    """Reference: fused_bias_dropout_residual_layer_norm — (x+bias) ->
    dropout -> +residual -> LN; the canonical transformer epilogue."""
    import paddle_tpu.nn.functional as NF
    from paddle_tpu.tensor import math as TM

    x, residual = ensure_tensor(x), ensure_tensor(residual)
    if bias is not None:
        x = TM.add(x, ensure_tensor(bias))
    if dropout_rate:
        x = NF.dropout(x, dropout_rate, training=training, mode=mode)
    out = TM.add(x, residual)
    if ln_scale is not None:
        out = NF.layer_norm(out, out.shape[-1:], weight=ln_scale, bias=ln_bias, epsilon=ln_epsilon)
    return out


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights, linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights, ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True, epsilon=1e-05, cache_kvs=None, pre_caches=None, seq_lens=None, rotary_embs=None, time_step=None, attn_mask=None, dropout_rate=0.0, activation="gelu", training=False, mode="upscale_in_train", trans_qkvw=True, ring_id=-1, name=None):
    """Reference: fused_multi_transformer (the serving decoder stack op) —
    applies L fused transformer layers in sequence."""
    out = ensure_tensor(x)
    L_layers = len(qkv_weights)
    if cache_kvs is not None or time_step is not None or pre_caches is not None or rotary_embs is not None or seq_lens is not None:
        # incremental decoding lives in the paged/serving tier
        raise NotImplementedError(
            "fused_multi_transformer: cache_kvs/time_step/pre_caches/"
            "rotary_embs/seq_lens (incremental decode) are served by "
            "paddle_tpu.incubate.nn.functional.block_multihead_attention / "
            "masked_multihead_attention and LlamaForCausalLM.generate"
        )
    if not trans_qkvw:
        # [E, 3*E]-layout weights carry no head count; the [3, nh, hd, E]
        # layout (trans_qkvw=True, the reference default) is required here
        raise ValueError(
            "fused_multi_transformer requires trans_qkvw=True weights "
            "([3, num_heads, head_dim, embed_dim]); the flat [E, 3E] layout "
            "does not encode the head count"
        )
    for i in range(L_layers):
        out = fused_multi_head_attention(
            out,
            qkv_weights[i],
            linear_weights[i],
            pre_layer_norm=pre_layer_norm,
            pre_ln_scale=ln_scales[i] if ln_scales else None,
            pre_ln_bias=ln_biases[i] if ln_biases else None,
            pre_ln_epsilon=epsilon,
            qkv_bias=qkv_biases[i] if qkv_biases else None,
            linear_bias=linear_biases[i] if linear_biases else None,
            attn_mask=attn_mask,
            dropout_rate=dropout_rate,
            training=training,
            mode=mode,
            transpose_qkv_wb=not trans_qkvw,
            num_heads=(qkv_weights[i].shape[1] if trans_qkvw else -1),
        )
        out = fused_feedforward(
            out,
            ffn1_weights[i],
            ffn2_weights[i],
            linear1_bias=ffn1_biases[i] if ffn1_biases else None,
            linear2_bias=ffn2_biases[i] if ffn2_biases else None,
            ln1_scale=ffn_ln_scales[i] if ffn_ln_scales else None,
            ln1_bias=ffn_ln_biases[i] if ffn_ln_biases else None,
            ln1_epsilon=epsilon,
            dropout1_rate=dropout_rate,
            dropout2_rate=dropout_rate,
            activation=activation,
            pre_layer_norm=pre_layer_norm,
            training=training,
            mode=mode,
        )
    return out, cache_kvs


__all__ += [
    "fused_multi_head_attention",
    "fused_feedforward",
    "fused_bias_dropout_residual_layer_norm",
    "fused_multi_transformer",
]


def fused_dot_product_attention(q, k, v, mask=None, scaling_factor=None,
                                dropout_prob=0.0, is_training=True,
                                is_causal_masking=False,
                                return_softmax=False):
    """Reference: python/paddle/incubate/nn/functional/
    fused_dot_product_attention.py (cuDNN fused attention, layout
    [B, S, N, H], int32/bool mask broadcast [B, 1, Sq, Sk]).

    TPU-native: the causal path routes through the Pallas flash kernel;
    masked paths compute the reference math in one jit region (XLA
    fuses).  `return_softmax` returns the probabilities — only available
    on the non-flash path, as flash never materializes them.  When
    `is_causal_masking` is True an explicit `mask` is IGNORED (reference
    docstring semantics); causal masking is bottom-right aligned for
    Sq != Sk on both paths.
    """
    q, k, v = ensure_tensor(q), ensure_tensor(k), ensure_tensor(v)
    head_dim = int(q.shape[-1])
    scale = (1.0 / math.sqrt(head_dim)) if scaling_factor is None else float(scaling_factor)
    dropout_active = dropout_prob > 0.0 and is_training
    if is_causal_masking and not return_softmax and not dropout_active:
        return apply(
            "flash_attention",
            lambda qv, kv, vv: _ops.flash_attention(qv, kv, vv, causal=True,
                                                    scale=scale),
            q, k, v)
    extras = [] if mask is None or is_causal_masking else [ensure_tensor(mask)]
    # probability dropout: key fetched at trace time, the canonical pattern
    # (nn/functional/common.py dropout)
    drop_key = _random.next_key() if dropout_active else None

    def _fn(qv, kv, vv, *rest):
        s = jnp.einsum("bqnh,bknh->bnqk", qv.astype(jnp.float32),
                       kv.astype(jnp.float32)) * scale
        if is_causal_masking:
            # bottom-right aligned (matches the flash kernel for Sq != Sk)
            causal = jnp.tril(jnp.ones((qv.shape[1], kv.shape[1]), bool),
                              k=kv.shape[1] - qv.shape[1])
            s = jnp.where(causal[None, None], s, -1e30)
        elif rest:
            keep = rest[0].astype(bool)  # [B, 1, Sq, Sk], True = attend
            s = jnp.where(keep, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        if dropout_active:
            keep = jax.random.bernoulli(drop_key, 1.0 - dropout_prob, p.shape)
            p = jnp.where(keep, p / (1.0 - dropout_prob), 0.0)
        o = jnp.einsum("bnqk,bknh->bqnh", p, vv.astype(jnp.float32))
        out = o.astype(qv.dtype)
        if return_softmax:
            return out, p.astype(qv.dtype)
        return out

    return apply("fused_dot_product_attention", _fn, q, k, v, *extras)


def fused_gate_attention(query, key=None, query_weight=None, key_weight=None,
                         value_weight=None, qkv_weight=None,
                         gate_linear_weight=None, gate_linear_bias=None,
                         out_linear_weight=None, out_linear_bias=None,
                         nonbatched_bias=None, attn_mask=None,
                         has_gating=True, merge_qkv=True,
                         use_flash_attn=False):
    """Reference: python/paddle/incubate/nn/functional/
    fused_gate_attention.py (AlphaFold-style gated self-attention over
    [B, msa, res, dim] inputs; merge_qkv=True uses one [3, N, H, D]
    weight, else separate [D, N, H] projections with key != query).

    TPU-native: one jit region of einsums — XLA fuses the projection +
    attention + gating chain; `use_flash_attn` is accepted for signature
    parity (the 5-D layout keeps the whole computation in one fusion, so
    a separate flash path buys nothing at AlphaFold's res_len scales).
    """
    query = ensure_tensor(query)
    if merge_qkv:
        if qkv_weight is None:
            raise ValueError("merge_qkv=True requires qkv_weight")
        if key is not None and key is not query:
            raise ValueError("merge_qkv=True is self-attention: key must be "
                             "None (reference semantics)")
        named = {"qkv_weight": ensure_tensor(qkv_weight)}
    else:
        missing = [n for n, w in (("query_weight", query_weight),
                                  ("key_weight", key_weight),
                                  ("value_weight", value_weight)) if w is None]
        if missing:
            raise ValueError(f"merge_qkv=False requires {missing}")
        named = {"query_weight": ensure_tensor(query_weight),
                 "key_weight": ensure_tensor(key_weight),
                 "value_weight": ensure_tensor(value_weight)}
        key = query if key is None else ensure_tensor(key)
        named["key_input"] = key
    if has_gating:
        if gate_linear_weight is None or gate_linear_bias is None:
            raise ValueError("has_gating=True requires gate_linear_weight "
                             "and gate_linear_bias")
        named["gate_w"] = ensure_tensor(gate_linear_weight)
        named["gate_b"] = ensure_tensor(gate_linear_bias)
    if out_linear_weight is None or out_linear_bias is None:
        raise ValueError("fused_gate_attention requires out_linear_weight "
                         "and out_linear_bias")
    named["out_w"] = ensure_tensor(out_linear_weight)
    named["out_b"] = ensure_tensor(out_linear_bias)
    if nonbatched_bias is not None:
        named["nb_bias"] = ensure_tensor(nonbatched_bias)
    if attn_mask is not None:
        named["attn_mask"] = ensure_tensor(attn_mask)
    keys = list(named)

    def _fn(qv, *vals):
        t = dict(zip(keys, vals))
        f32 = jnp.float32
        if merge_qkv:
            # qkv_weight [3, N, H, D]; q/k/v: [B, M, R, D] @ w -> [B, M, R, N, H]
            qkv = jnp.einsum("bmrd,snhd->sbmrnh", qv.astype(f32),
                             t["qkv_weight"].astype(f32))
            q_p, k_p, v_p = qkv[0], qkv[1], qkv[2]
            head_dim = q_p.shape[-1]
        else:
            kv_in = t["key_input"].astype(f32)
            q_p = jnp.einsum("bmrd,dnh->bmrnh", qv.astype(f32),
                             t["query_weight"].astype(f32))
            k_p = jnp.einsum("bmkd,dnh->bmknh", kv_in, t["key_weight"].astype(f32))
            v_p = jnp.einsum("bmkd,dnh->bmknh", kv_in, t["value_weight"].astype(f32))
            head_dim = q_p.shape[-1]
        q_p = q_p * (float(head_dim) ** -0.5)
        logits = jnp.einsum("bmqnh,bmknh->bmnqk", q_p, k_p)
        if "attn_mask" in t:
            mask = t["attn_mask"].astype(f32)
            logits = logits + (1.0 - mask) * -1e9
        if "nb_bias" in t:
            logits = logits + t["nb_bias"].astype(f32)[:, None]
        probs = jax.nn.softmax(logits, axis=-1)
        ctx = jnp.einsum("bmnqk,bmknh->bmqnh", probs, v_p)
        if has_gating:
            gate = jnp.einsum("bmrd,dnh->bmrnh", qv.astype(f32),
                              t["gate_w"].astype(f32)) + t["gate_b"].astype(f32)
            ctx = ctx * jax.nn.sigmoid(gate)
        out = jnp.einsum("bmrnh,nhd->bmrd", ctx, t["out_w"].astype(f32))
        out = out + t["out_b"].astype(f32)
        return out.astype(qv.dtype)

    return apply("fused_gate_attention", _fn, query, *named.values())
