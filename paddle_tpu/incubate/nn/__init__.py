"""paddle.incubate.nn parity: fused-op functional API + fused layers."""

from . import functional  # noqa: F401
from .layers import FusedRMSNorm, FusedLayerNorm  # noqa: F401
