"""paddle.incubate.nn parity: fused-op functional API + fused layers."""

from . import functional  # noqa: F401
from .layers import (  # noqa: F401
    FusedFeedForward,
    FusedLayerNorm,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedRMSNorm,
    FusedTransformerEncoderLayer,
)
