"""paddle.incubate.nn parity: fused-op functional API + fused layers."""

from . import functional  # noqa: F401
from .layers import (  # noqa: F401
    FusedBiasDropoutResidualLayerNorm,
    FusedDropoutAdd,
    FusedEcMoe,
    FusedFeedForward,
    FusedLayerNorm,
    FusedLinear,
    FusedMultiHeadAttention,
    FusedMultiTransformer,
    FusedRMSNorm,
    FusedTransformerEncoderLayer,
)
