"""Fused layer modules over the Pallas kernels (reference:
python/paddle/incubate/nn/layer/fused_transformer.py lineage)."""

from __future__ import annotations

import paddle_tpu.nn as nn
from paddle_tpu.nn import initializer as I

from . import functional as F


class FusedRMSNorm(nn.Layer):
    def __init__(self, hidden_size, epsilon=1e-6, dtype="float32"):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], default_initializer=I.Constant(1.0), dtype=dtype)

    def forward(self, x, residual=None):
        return F.fused_rms_norm(x, self.weight, epsilon=self.epsilon, residual=residual)


class FusedLayerNorm(nn.Layer):
    def __init__(self, hidden_size, epsilon=1e-5, dtype="float32"):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], default_initializer=I.Constant(1.0), dtype=dtype)
        self.bias = self.create_parameter([hidden_size], default_initializer=I.Constant(0.0), dtype=dtype)

    def forward(self, x, residual=None):
        return F.fused_layer_norm(x, self.weight, self.bias, epsilon=self.epsilon, residual=residual)
