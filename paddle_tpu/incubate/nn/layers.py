"""Fused layer modules over the Pallas kernels (reference:
python/paddle/incubate/nn/layer/fused_transformer.py lineage)."""

from __future__ import annotations

import paddle_tpu.nn as nn
from paddle_tpu.nn import initializer as I

from . import functional as F


class FusedRMSNorm(nn.Layer):
    def __init__(self, hidden_size, epsilon=1e-6, dtype="float32"):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], default_initializer=I.Constant(1.0), dtype=dtype)

    def forward(self, x, residual=None):
        return F.fused_rms_norm(x, self.weight, epsilon=self.epsilon, residual=residual)


class FusedLayerNorm(nn.Layer):
    def __init__(self, hidden_size, epsilon=1e-5, dtype="float32"):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], default_initializer=I.Constant(1.0), dtype=dtype)
        self.bias = self.create_parameter([hidden_size], default_initializer=I.Constant(0.0), dtype=dtype)

    def forward(self, x, residual=None):
        return F.fused_layer_norm(x, self.weight, self.bias, epsilon=self.epsilon, residual=residual)


class FusedMultiHeadAttention(nn.Layer):
    """Reference python/paddle/incubate/nn/layer/fused_transformer.py
    FusedMultiHeadAttention: pre/post-LN + qkv + attention + out proj in one
    block.  TPU-native: the fusion is XLA's (norm+matmul epilogues) plus the
    flash kernel via scaled_dot_product_attention."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.0, attn_dropout_rate=0.0,
                 normalize_before=False, need_weights=False, qkv_weight_attr=None,
                 epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.norm = nn.LayerNorm(embed_dim, epsilon=epsilon)
        self.attn = nn.MultiHeadAttention(embed_dim, num_heads, attn_dropout_rate)
        self.dropout = nn.Dropout(dropout_rate)

    def forward(self, x, attn_mask=None, cache=None):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        out = self.attn(x, attn_mask=attn_mask)
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(nn.Layer):
    """Reference FusedFeedForward: LN + linear + act + linear + residual."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.norm = nn.LayerNorm(d_model, epsilon=epsilon)
        self.linear1 = nn.Linear(d_model, dim_feedforward)
        self.linear2 = nn.Linear(dim_feedforward, d_model)
        self.drop_act = nn.Dropout(act_dropout_rate if act_dropout_rate is not None else dropout_rate)
        self.drop = nn.Dropout(dropout_rate)
        self.act = activation

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        residual = x
        if self.normalize_before:
            x = self.norm(x)
        h = self.drop_act(getattr(F, self.act)(self.linear1(x)))
        out = residual + self.drop(self.linear2(h))
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    """Reference FusedTransformerEncoderLayer = FusedMHA + FusedFFN."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None, act_dropout_rate=None,
                 normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate if attn_dropout_rate is not None else dropout_rate,
            normalize_before=normalize_before,
        )
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
        )

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedMultiTransformer(nn.Layer):
    """Reference FusedMultiTransformer (the serving decoder stack op,
    python/paddle/incubate/nn/layer/fused_transformer.py:1380): N pre-LN
    decoder blocks in one module.  TPU-native: blocks are python, the fusion
    is whole-graph XLA under jit; causal decode attention rides the flash /
    paged kernels."""

    def __init__(self, embed_dim, num_heads, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, num_layers=1, epsilon=1e-5):
        super().__init__()
        self.layers = nn.LayerList([
            FusedTransformerEncoderLayer(
                embed_dim, num_heads, dim_feedforward, dropout_rate,
                activation=activation, normalize_before=normalize_before,
            )
            for _ in range(num_layers)
        ])

    def forward(self, x, attn_mask=None, caches=None):
        for layer in self.layers:
            x = layer(x, src_mask=attn_mask)
        return x


class FusedLinear(nn.Layer):
    """reference: python/paddle/incubate/nn/layer/fused_linear.py — Linear
    over the fused matmul+bias functional."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        shape = [out_features, in_features] if transpose_weight else [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True) if bias_attr is not False else None
        self._transpose = transpose_weight

    def forward(self, x):
        return F.fused_linear(x, self.weight, self.bias, transpose_weight=self._transpose)


class FusedDropoutAdd(nn.Layer):
    """reference: incubate/nn/layer/fused_dropout_add.py."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.mode = p, mode

    def forward(self, x, y):
        return F.fused_dropout_add(x, y, p=self.p, training=self.training, mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """reference: incubate/nn/layer/fused_transformer.py
    FusedBiasDropoutResidualLayerNorm — owns the LN affine params."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None, bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.ln_scale = self.create_parameter([embed_dim], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], attr=bias_attr, is_bias=True)
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            dropout_rate=self.dropout_rate, ln_epsilon=self.epsilon, training=self.training,
        )


class FusedEcMoe(nn.Layer):
    """reference: incubate/nn/layer/fused_ec_moe.py — expert-choice MoE
    block over the fused_ec_moe functional."""

    def __init__(self, hidden_size, inter_size, num_experts, act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        # reference shapes (fused_ec_moe.py docstring): weights [E, D, F] /
        # [E, F, D], biases [E, 1, F] / [E, 1, D]
        self.bmm_weight0 = self.create_parameter([num_experts, hidden_size, inter_size], attr=weight_attr)
        self.bmm_bias0 = self.create_parameter([num_experts, 1, inter_size], attr=bias_attr, is_bias=True)
        self.bmm_weight1 = self.create_parameter([num_experts, inter_size, hidden_size], attr=weight_attr)
        self.bmm_bias1 = self.create_parameter([num_experts, 1, hidden_size], attr=bias_attr, is_bias=True)
        self.act_type = act_type
        if self.act_type not in ("gelu", "relu"):
            raise NotImplementedError("FusedEcMoe supports gelu/relu")

    def forward(self, x, gate):
        """x: [B, S, D]; gate: per-token logits [B, S, E] (reference
        forward contract)."""
        return F.fused_ec_moe(
            x, gate, self.bmm_weight0, self.bmm_bias0,
            self.bmm_weight1, self.bmm_bias1, act_type=self.act_type,
        )
