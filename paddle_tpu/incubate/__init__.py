"""paddle.incubate parity namespace."""

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import distributed  # noqa: F401
from . import asp  # noqa: F401

# reference paddle.incubate top-level __all__ closure
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from paddle_tpu.geometric import (  # noqa: F401
    reindex_graph as graph_reindex,
    sample_neighbors as graph_sample_neighbors,
    segment_max,
    segment_mean,
    segment_min,
    segment_sum,
    send_u_recv as graph_send_recv,
)
from . import autograd  # noqa: F401


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes, sorted_eids=None, return_eids=False, name=None):
    """Multi-hop neighbor sampling (reference:
    python/paddle/incubate/operators/graph_khop_sampler.py): repeated
    sample_neighbors over CSC (row, colptr) for each hop.  Host-side
    sampling op (data-dependent sizes), like the reference's usage in the
    data pipeline."""
    import numpy as np

    from paddle_tpu._core.tensor import Tensor
    from paddle_tpu.geometric import sample_neighbors

    if return_eids or sorted_eids is not None:
        raise NotImplementedError(
            "graph_khop_sampler: return_eids/sorted_eids not supported; use "
            "paddle.geometric.sample_neighbors(..., eids=, return_eids=True) per hop"
        )
    nodes = input_nodes
    edge_src, edge_dst = [], []
    for k in sample_sizes:
        srcs, counts = sample_neighbors(row, colptr, nodes, sample_size=int(k))
        sv = np.asarray(srcs._value)
        cv = np.asarray(counts._value)
        dst = np.repeat(np.asarray(nodes._value if isinstance(nodes, Tensor) else nodes), cv)
        edge_src.append(sv)
        edge_dst.append(dst)
        nodes = Tensor(srcs._value)
    es = np.concatenate(edge_src) if edge_src else np.zeros(0, np.int64)
    ed = np.concatenate(edge_dst) if edge_dst else np.zeros(0, np.int64)
    seeds = np.asarray(input_nodes._value if isinstance(input_nodes, Tensor) else input_nodes)
    uniq = np.unique(np.concatenate([seeds, es]))
    import jax.numpy as jnp

    return (
        Tensor(jnp.asarray(es)),
        Tensor(jnp.asarray(ed)),
        Tensor(jnp.asarray(uniq)),
        Tensor(jnp.asarray(np.searchsorted(uniq, es))),
    )


def identity_loss(x, reduction="none"):
    """reference: python/paddle/incubate/nn/functional/identity_loss — marks
    x as the loss (IPU lineage); reduces per `reduction`."""
    from paddle_tpu.tensor._ops_common import ensure_tensor

    x = ensure_tensor(x)
    if reduction in ("mean", 1):
        return x.mean()
    if reduction in ("sum", 0):
        return x.sum()
    return x


def softmax_mask_fuse(x, mask, name=None):
    """Fused attention-mask + softmax (reference:
    python/paddle/incubate/operators/softmax_mask_fuse.py): softmax(x + mask)
    in fp32 — XLA fuses this into one kernel, which is the entire point of
    the reference's handwritten CUDA op."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.tensor._ops_common import apply, ensure_tensor

    x, mask = ensure_tensor(x), ensure_tensor(mask)

    def _fn(v, m):
        return jax.nn.softmax(v.astype(jnp.float32) + m.astype(jnp.float32), axis=-1).astype(v.dtype)

    return apply("softmax_mask_fuse", _fn, x, mask)


def softmax_mask_fuse_upper_triangle(x):
    """reference: softmax_mask_fuse_upper_triangle — causal-masked softmax
    (upper triangle masked out) without materializing the mask."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.tensor._ops_common import apply, ensure_tensor

    x = ensure_tensor(x)

    def _fn(v):
        S, T = v.shape[-2], v.shape[-1]
        i = jax.lax.broadcasted_iota(jnp.int32, (S, T), 0)
        j = jax.lax.broadcasted_iota(jnp.int32, (S, T), 1)
        vf = jnp.where(j <= i, v.astype(jnp.float32), -jnp.inf)
        return jax.nn.softmax(vf, axis=-1).astype(v.dtype)

    return apply("softmax_mask_fuse_upper_triangle", _fn, x)
