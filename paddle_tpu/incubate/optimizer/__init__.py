"""paddle.incubate.optimizer parity: LookAhead, ModelAverage, GradientMerge-
style accumulation (reference: python/paddle/incubate/optimizer/)."""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.optimizer.optimizer import Optimizer

from paddle_tpu.optimizer.lbfgs import LBFGS  # noqa: F401

__all__ = ["LookAhead", "ModelAverage", "LARS", "GradientMergeOptimizer", "DistributedFusedLamb", "LBFGS"]


class LookAhead(Optimizer):
    """Lookahead wrapper: slow weights pulled toward fast weights every k steps
    (reference python/paddle/incubate/optimizer/lookahead.py)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_num = 0
        # slow weights snapshot the INITIAL fast weights (reference
        # lookahead.py) so the first k-step sync interpolates from w_0
        self._slow = {id(p): p._value for p in inner_optimizer._parameter_list}

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self.inner_optimizer._parameter_list:
                key = id(p)
                slow = self._slow[key] + self.alpha * (p._value - self._slow[key])
                self._slow[key] = slow
                p._bind(slow)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_num
        return sd

    def set_state_dict(self, sd):
        self._step_num = sd.pop("lookahead_step", 0)
        self.inner_optimizer.set_state_dict(sd)


class ModelAverage(Optimizer):
    """Maintains a running average of parameters; `apply()` swaps averages in
    (reference python/paddle/incubate/optimizer/modelaverage.py)."""

    def __init__(self, average_window_rate=0.15, parameters=None, min_average_window=10000, max_average_window=10000, name=None):
        self._parameter_list = list(parameters or [])
        self._sums = {id(p): p._value * 0 for p in self._parameter_list}
        self._count = 0
        self._backup = None

    def step(self):
        for p in self._parameter_list:
            self._sums[id(p)] = self._sums[id(p)] + p._value
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._value for p in self._parameter_list}
        for p in self._parameter_list:
            if self._count:
                p._bind(self._sums[id(p)] / self._count)

    def restore(self, executor=None):
        if self._backup:
            for p in self._parameter_list:
                p._bind(self._backup[id(p)])
            self._backup = None

    def clear_grad(self):
        for p in self._parameter_list:
            p.clear_grad()


class LARS(Optimizer):
    """Layer-wise Adaptive Rate Scaling (reference
    python/paddle/incubate/optimizer/... lars_momentum op,
    paddle/phi/kernels/gpu/lars_momentum_kernel.cu): momentum SGD with a
    per-layer trust ratio ||w|| / (||g|| + wd*||w||)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 exclude_from_weight_decay=None, epsilon=0.0, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._wd = lars_weight_decay
        self._eps = epsilon
        self._exclude = list(exclude_from_weight_decay or [])

    def _single_update(self, p, g, lr):
        import jax.numpy as jnp

        g32 = g.astype(jnp.float32)
        master = p._value.astype(jnp.float32)
        wd = self._wd
        if any(tag in (p.name or "") for tag in self._exclude):
            wd = 0.0
        w_norm = jnp.linalg.norm(master)
        g_norm = jnp.linalg.norm(g32)
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._coeff * w_norm / (g_norm + wd * w_norm + self._eps),
            1.0,
        )
        vel = self._acc("velocity", p, dtype=jnp.float32)
        new_v = self._momentum * vel._value + lr * trust * (g32 + wd * master)
        vel._bind(new_v)
        return master - new_v


class GradientMergeOptimizer:
    """Accumulate grads over k_steps micro-steps, apply the inner optimizer
    on the k-th (reference python/paddle/incubate/optimizer/gradient_merge.py
    and the auto-parallel gradient_merge pass).

    Fully functional/trace-stable: the micro-step counter is DEVICE state and
    the apply-vs-skip decision is a traced select (snapshot params/
    accumulators, run the inner step, keep the old state where the counter
    says skip) — so one compiled TrainStep serves every micro-step, exactly
    like the GradScaler's functional skip.  Eagerly the same math runs on
    concrete values.
    """

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        import jax.numpy as jnp

        self.inner = inner_optimizer
        self._k = int(k_steps)
        self._avg = avg
        self._micro_t = Tensor(jnp.asarray(0, jnp.int32))

    def __getattr__(self, item):
        return getattr(self.__dict__["inner"], item)

    def step(self):
        import jax

        import jax.numpy as jnp

        inner = self.inner
        k = self._k
        new_micro = self._micro_t._value + 1
        apply_pred = (new_micro % k) == 0
        params = [p for p in inner._parameter_list if not p.stop_gradient]

        if not isinstance(apply_pred, jax.core.Tracer):
            # eager: exact python semantics (incl. inner._step_count cadence)
            do_apply = bool(apply_pred)
            for p in params:
                if p.grad is None:
                    continue
                acc = inner._acc("grad_merge", p, dtype=jnp.float32)
                new = acc._value + p.grad._value.astype(jnp.float32)
                if do_apply:
                    p.grad = Tensor(new / k if self._avg else new)
                    acc._bind(jnp.zeros_like(new))
                else:
                    acc._bind(new)
                    p.grad = None  # consumed into the merge buffer
            if do_apply:
                inner.step()
            self._micro_t._bind(new_micro % k)
            return

        # traced (inside a compiled step): functional skip — accumulate
        # always, run the inner update, select old state back where the
        # counter says skip.  Freshly-created accumulators are restored to
        # their captured INIT value on skip (an _acc spy records it), so a
        # skipped micro-step cannot pollute Adam moments / master weights.
        # Python-level inner._step_count freezes at trace time (same caveat
        # as static capture, optimizer.py _static_minimize note).
        for p in params:
            if p.grad is None:
                continue
            acc = inner._acc("grad_merge", p, dtype=jnp.float32)
            new = acc._value + p.grad._value.astype(jnp.float32)
            acc._bind(jnp.where(apply_pred, jnp.zeros_like(new), new))
            p.grad = Tensor(new / k if self._avg else new)
        snap_p = [(p, p._value) for p in params]
        snap_a = {kk: t._value for kk, t in inner._accumulators.items()}
        fresh_inits = {}
        orig_acc_fn = inner._acc

        def acc_spy(name, p, init=None, dtype=None):
            key = (name, id(p))
            existed = key in inner._accumulators
            t = orig_acc_fn(name, p, init=init, dtype=dtype)
            if not existed and key not in snap_a:
                fresh_inits[key] = t._value
            return t

        inner._acc = acc_spy
        try:
            inner.step()
        finally:
            del inner._acc
        for p, old in snap_p:
            p._bind(jnp.where(apply_pred, p._value, old))
        for kk, t in inner._accumulators.items():
            old = snap_a.get(kk, fresh_inits.get(kk))
            if old is not None and old.shape == t._value.shape:
                t._bind(jnp.where(apply_pred, t._value, old))
        self._micro_t._bind(new_micro % k)

    def _journaled_step(self, params):
        """Zero-grad dry run through OUR step() (so the grad_merge
        accumulators exist before a TrainStep collects state), then roll
        every mutation back — the Optimizer._journaled_step contract."""
        import jax.numpy as jnp

        from paddle_tpu._core.autograd import no_grad

        inner = self.inner
        pre_acc = {k: t._value for k, t in inner._accumulators.items()}
        fresh = {}
        orig_acc_fn = inner._acc

        def spy(name, p, init=None, dtype=None):
            key = (name, id(p))
            existed = key in inner._accumulators
            t = orig_acc_fn(name, p, init=init, dtype=dtype)
            if not existed and key not in pre_acc:
                fresh[key] = t._value
            return t

        saved = [(p, p._value, p.grad) for p in params]
        saved_micro = self._micro_t._value
        saved_count = inner._step_count
        inner._acc = spy
        try:
            for p in params:
                p.grad = Tensor(jnp.zeros_like(p._value))
            with no_grad():
                self.step()
        finally:
            del inner._acc
            for p, v, g in saved:
                p._bind(v)
                p.grad = g
            inner._step_count = saved_count
            self._micro_t._bind(saved_micro)
            for k, t in inner._accumulators.items():
                if k in pre_acc:
                    t._bind(pre_acc[k])
                elif k in fresh:
                    t._bind(fresh[k])

    def clear_grad(self, set_to_zero: bool = False):
        self.inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def opt_state_tensors(self):
        return self.inner.opt_state_tensors() + [self._micro_t]

    def state_dict(self):
        return self.inner.state_dict()

    def set_state_dict(self, state):
        return self.inner.set_state_dict(state)


class DistributedFusedLamb:
    """Reference python/paddle/incubate/optimizer/distributed_fused_lamb.py:
    a CUDA kernel fusing multi-tensor LAMB with ZeRO-sharded states and
    fused allreduce.  TPU-native: the python Lamb update is already fused by
    XLA across the whole parameter sweep inside a compiled step, grads are
    reduce-scattered by GSPMD, and state sharding comes from
    ShardedTrainStep's accumulator policy — so this class delegates every
    Optimizer duty to Lamb (clip_after_allreduce etc. accepted; the XLA
    schedule subsumes them)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, clip_after_allreduce=True,
                 is_grad_scaled_by_nranks=True, alignment=128, nproc_per_node=None,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, name=None):
        from paddle_tpu.optimizer.optimizers import Lamb

        impl = Lamb(
            learning_rate=learning_rate,
            lamb_weight_decay=lamb_weight_decay,
            beta1=beta1, beta2=beta2, epsilon=epsilon,
            parameters=parameters, grad_clip=grad_clip,
            exclude_from_weight_decay_fn=exclude_from_weight_decay_fn,
        )
        if gradient_accumulation_steps > 1:
            impl = GradientMergeOptimizer(impl, gradient_accumulation_steps)
        self._impl = impl

    def __getattr__(self, item):
        # full delegation: the live impl owns all optimizer state
        return getattr(self.__dict__["_impl"], item)

    def __setattr__(self, key, value):
        if key == "_impl":
            object.__setattr__(self, key, value)
        else:
            setattr(self._impl, key, value)
