"""paddle.incubate.optimizer parity: LookAhead, ModelAverage, GradientMerge-
style accumulation (reference: python/paddle/incubate/optimizer/)."""

from __future__ import annotations

import paddle_tpu as paddle
from paddle_tpu._core.tensor import Tensor
from paddle_tpu.optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """Lookahead wrapper: slow weights pulled toward fast weights every k steps
    (reference python/paddle/incubate/optimizer/lookahead.py)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_num = 0
        # slow weights snapshot the INITIAL fast weights (reference
        # lookahead.py) so the first k-step sync interpolates from w_0
        self._slow = {id(p): p._value for p in inner_optimizer._parameter_list}

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self.inner_optimizer._parameter_list:
                key = id(p)
                slow = self._slow[key] + self.alpha * (p._value - self._slow[key])
                self._slow[key] = slow
                p._bind(slow)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._step_num
        return sd

    def set_state_dict(self, sd):
        self._step_num = sd.pop("lookahead_step", 0)
        self.inner_optimizer.set_state_dict(sd)


class ModelAverage(Optimizer):
    """Maintains a running average of parameters; `apply()` swaps averages in
    (reference python/paddle/incubate/optimizer/modelaverage.py)."""

    def __init__(self, average_window_rate=0.15, parameters=None, min_average_window=10000, max_average_window=10000, name=None):
        self._parameter_list = list(parameters or [])
        self._sums = {id(p): p._value * 0 for p in self._parameter_list}
        self._count = 0
        self._backup = None

    def step(self):
        for p in self._parameter_list:
            self._sums[id(p)] = self._sums[id(p)] + p._value
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._value for p in self._parameter_list}
        for p in self._parameter_list:
            if self._count:
                p._bind(self._sums[id(p)] / self._count)

    def restore(self, executor=None):
        if self._backup:
            for p in self._parameter_list:
                p._bind(self._backup[id(p)])
            self._backup = None

    def clear_grad(self):
        for p in self._parameter_list:
            p.clear_grad()
