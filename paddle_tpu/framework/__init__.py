"""Framework utilities (reference: python/paddle/framework/)."""

from .io_utils import load, save  # noqa: F401
from paddle_tpu._core.random import seed  # noqa: F401
from paddle_tpu._core.random import get_rng_state, set_rng_state  # noqa: F401
from . import op_registry  # noqa: F401,E402
