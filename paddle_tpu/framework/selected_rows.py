"""SelectedRows — row-sparse gradients for embedding-class parameters.

Reference: paddle/phi/core/selected_rows.h (rows + value tensor + height)
and the sparse-gradient path of embedding / lookup_table
(paddle/phi/kernels/cpu/embedding_grad_kernel.cc sparse branch, the Adam
lazy_mode row updates in paddle/phi/kernels/funcs/adam_functors.h).

TPU-native role: a large-vocab embedding backward that materializes a dense
[V, H] gradient wastes HBM bandwidth on rows that are all zero.  With
Embedding(sparse=True) the backward instead produces a SelectedRows —
(rows[k], values[k, H], height=V) — and the optimizer applies a
segment-sum/scatter row update touching only the k looked-up rows, the
reference's lazy_mode semantics."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["SelectedRows"]


class SelectedRows:
    """rows[i] indexes the parameter's dim-0; values[i] is that row's grad
    contribution.  Duplicate rows are allowed (coalesce() merges them)."""

    __slots__ = ("rows", "values", "height")

    def __init__(self, rows, values, height):
        self.rows = jnp.asarray(rows).reshape(-1)
        self.values = jnp.asarray(values)
        self.height = int(height)
        if self.values.shape[0] != self.rows.shape[0]:
            raise ValueError(
                f"SelectedRows: {self.rows.shape[0]} rows vs "
                f"{self.values.shape[0]} value rows"
            )

    @property
    def shape(self):
        return (self.height,) + tuple(self.values.shape[1:])

    @property
    def dtype(self):
        return self.values.dtype

    def coalesce(self) -> "SelectedRows":
        """Merge duplicate rows (segment-sum over the unique row set) —
        reference MergeAdd on SelectedRows.  Eager-only (unique output size
        is data-dependent)."""
        rows = np.asarray(self.rows)
        urows, inv = np.unique(rows, return_inverse=True)
        import jax.ops

        merged = jax.ops.segment_sum(
            self.values, jnp.asarray(inv), num_segments=int(urows.shape[0])
        )
        return SelectedRows(jnp.asarray(urows), merged, self.height)

    def to_dense(self):
        dense = jnp.zeros(self.shape, self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def accumulate(self, other):
        """Gradient accumulation across backward calls: concatenation (the
        optimizer coalesces once at update time)."""
        if isinstance(other, SelectedRows):
            if other.height != self.height:
                raise ValueError("SelectedRows height mismatch in accumulate")
            return SelectedRows(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]),
                self.height,
            )
        # dense + sparse -> dense
        return other + self.to_dense()

    def __repr__(self):
        return (
            f"SelectedRows(height={self.height}, nnz_rows={self.rows.shape[0]}, "
            f"row_width={self.values.shape[1:]}, dtype={self.values.dtype})"
        )
