"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:721,960).

Serialization format: pickle of a nested structure whose leaf Tensors become
numpy arrays (portable, framework-agnostic) — same pickle-compatible contract
as the reference's state_dict files, without the protobuf program baggage.
"""

from __future__ import annotations

import contextlib
import os
import pickle
import threading

import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = ["save", "load", "wait_async_save", "atomic_write", "spawn_async_write"]

_MAGIC = b"PDTPU1\x00"


@contextlib.contextmanager
def atomic_write(path, mode: str = "wb"):
    """Write `path` atomically: yields a file handle onto a same-directory
    temp file, fsyncs and `os.replace`s it over `path` on success, unlinks
    the temp on failure.  A crash at ANY point leaves either the previous
    file contents or the new ones — never a torn file.  Shared by
    `framework.io_utils.save` and `distributed/checkpoint` (the checkpoint
    commit protocol is built out of this primitive)."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
    except BaseException:
        f.close()
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _to_portable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._value), "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _to_portable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_to_portable(v) for v in obj]
        return out if isinstance(obj, list) else tuple(out)
    return obj


def _from_portable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"])
            t.stop_gradient = obj.get("stop_gradient", True)
            return t
        return {k: _from_portable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_from_portable(v, return_numpy) for v in obj]
        return out if isinstance(obj, list) else tuple(out)
    return obj


_async_saves: list = []  # (thread, path, error_box)
_path_locks: dict = {}
_path_locks_guard = threading.Lock()


def _lock_for(path):
    with _path_locks_guard:
        return _path_locks.setdefault(os.path.abspath(path), threading.Lock())


def spawn_async_write(write_fn, path):
    """Run `write_fn` on a supervised background thread.  The thread is
    registered so `wait_async_save()` joins it and re-raises its failure —
    the fire-and-forget daemon-thread pattern loses checkpoints silently.
    Returns the Thread (callers may also join it directly)."""
    err: list = []

    def _guarded():
        try:
            write_fn()
        except BaseException as e:  # re-raised by wait_async_save
            err.append(e)

    t = threading.Thread(target=_guarded, name=f"paddle_tpu_save:{os.path.basename(path)}")
    t.start()
    _async_saves.append((t, path, err))
    return t


def save(obj, path, protocol=4, async_save=False, **configs):
    """paddle.save (reference python/paddle/framework/io.py:721).

    async_save=True EXCEEDS the reference (SURVEY.md §5 notes the reference
    has no async checkpointing): the device->host snapshot happens
    synchronously (so training may immediately mutate the live state), the
    pickle+disk write runs on a background thread — orbax-style.  Writes go
    to a temp file then os.replace (atomic: a crash mid-write keeps the
    previous checkpoint intact) and same-path saves are serialized.  Call
    `wait_async_save()` to join outstanding writes — it re-raises the first
    background error (a silently lost checkpoint is worse than a crash)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    portable = _to_portable(obj)  # snapshot: host copies of device arrays

    def _write():
        with _lock_for(path):
            with atomic_write(path) as f:
                f.write(_MAGIC)
                pickle.dump(portable, f, protocol=protocol)

    if not async_save:
        _write()
        return

    spawn_async_write(_write, path)


def wait_async_save():
    """Join all outstanding async checkpoint writes; re-raise the first
    background failure."""
    global _async_saves
    pending, _async_saves = _async_saves, []
    first_err = None
    for t, path, err in pending:
        t.join()
        if err and first_err is None:
            first_err = (path, err[0])
    if first_err is not None:
        path, e = first_err
        raise RuntimeError(f"async checkpoint write to {path!r} failed") from e


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if head != _MAGIC:
            f.seek(0)
        obj = pickle.load(f)
    return _from_portable(obj, return_numpy=return_numpy)
