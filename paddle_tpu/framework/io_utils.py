"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:721,960).

Serialization format: pickle of a nested structure whose leaf Tensors become
numpy arrays (portable, framework-agnostic) — same pickle-compatible contract
as the reference's state_dict files, without the protobuf program baggage.
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = ["save", "load"]

_MAGIC = b"PDTPU1\x00"


def _to_portable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._value), "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _to_portable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_to_portable(v) for v in obj]
        return out if isinstance(obj, list) else tuple(out)
    return obj


def _from_portable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            if return_numpy:
                return obj["data"]
            t = Tensor(obj["data"])
            t.stop_gradient = obj.get("stop_gradient", True)
            return t
        return {k: _from_portable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        out = [_from_portable(v, return_numpy) for v in obj]
        return out if isinstance(obj, list) else tuple(out)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(_to_portable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if head != _MAGIC:
            f.seek(0)
        obj = pickle.load(f)
    return _from_portable(obj, return_numpy=return_numpy)
