"""Framework-level defaults and dtype-info utilities.

Reference surface: paddle.get_default_dtype / set_default_dtype
(python/paddle/base/framework.py), paddle.finfo / paddle.iinfo
(paddle/fluid/pybind/pybind.cc finfo/iinfo bindings), paddle.set_printoptions
(python/paddle/tensor/to_string.py), paddle.batch (python/paddle/batch.py),
paddle.check_shape (python/paddle/base/data_feeder.py:227),
paddle.disable_signal_handler.

TPU-native: the default dtype is the existing FLAGS_default_dtype flag (one
source of truth with the creation ops); finfo/iinfo delegate to ml_dtypes via
jnp so bfloat16/float8 variants are covered, which numpy alone is not.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from paddle_tpu._core import flags as _flags
from paddle_tpu._core.dtype import to_jax_dtype

__all__ = [
    "get_default_dtype",
    "set_default_dtype",
    "finfo",
    "iinfo",
    "set_printoptions",
    "batch",
    "check_shape",
    "disable_signal_handler",
]


def get_default_dtype():
    """Default float dtype used by creation ops when dtype=None."""
    return str(_flags.flag("FLAGS_default_dtype"))


def set_default_dtype(d):
    jd = to_jax_dtype(d)  # framework-wide width policy: float64 narrows to float32
    name = jnp.dtype(jd).name
    if name not in ("float16", "bfloat16", "float32"):
        raise TypeError(f"set_default_dtype only supports float dtypes, got {d}")
    _flags.set_flags({"FLAGS_default_dtype": name})


class finfo:
    """Floating-point type info (paddle.finfo parity: eps/min/max/tiny/
    smallest_normal/resolution/bits/dtype fields)."""

    def __init__(self, dtype):
        fi = jnp.finfo(to_jax_dtype(dtype))
        self.dtype = str(np.dtype(fi.dtype).name) if fi.dtype != jnp.bfloat16 else "bfloat16"
        self.eps = float(fi.eps)
        self.min = float(fi.min)
        self.max = float(fi.max)
        self.tiny = float(fi.tiny)
        self.smallest_normal = float(fi.tiny)
        self.resolution = float(fi.resolution)
        self.bits = int(fi.bits)

    def __repr__(self):
        return (
            f"finfo(resolution={self.resolution}, min={self.min}, max={self.max}, "
            f"eps={self.eps}, bits={self.bits}, dtype={self.dtype})"
        )


class iinfo:
    """Integer type info (paddle.iinfo parity: min/max/bits/dtype)."""

    def __init__(self, dtype):
        ii = jnp.iinfo(to_jax_dtype(dtype))
        self.dtype = str(np.dtype(ii.dtype).name)
        self.min = int(ii.min)
        self.max = int(ii.max)
        self.bits = int(ii.bits)

    def __repr__(self):
        return f"iinfo(min={self.min}, max={self.max}, bits={self.bits}, dtype={self.dtype})"


_print_opts = {}


def set_printoptions(precision=None, threshold=None, edgeitems=None, sci_mode=None, linewidth=None):
    """Tensor print formatting (paddle.set_printoptions parity); backed by
    numpy printoptions since Tensor.__repr__ renders via numpy."""
    kw = {}
    if precision is not None:
        kw["precision"] = int(precision)
        _print_opts["precision"] = int(precision)
    if threshold is not None:
        kw["threshold"] = int(threshold)
        _print_opts["threshold"] = int(threshold)
    if edgeitems is not None:
        kw["edgeitems"] = int(edgeitems)
        _print_opts["edgeitems"] = int(edgeitems)
    if linewidth is not None:
        kw["linewidth"] = int(linewidth)
        _print_opts["linewidth"] = int(linewidth)
    if sci_mode is not None:
        kw["suppress"] = not bool(sci_mode)
        _print_opts["sci_mode"] = bool(sci_mode)
    np.set_printoptions(**kw)


def batch(reader, batch_size, drop_last=False):
    """Batched-reader decorator (reference: python/paddle/batch.py): wraps a
    sample generator factory into a mini-batch generator factory."""

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == int(batch_size):
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    if int(batch_size) <= 0:
        raise ValueError("batch_size should be a positive integer")
    return batch_reader


def check_shape(shape, op_name="", expected_shape_type=(list, tuple), expected_element_type=(int,), expected_tensor_dtype=("int32", "int64")):
    """Static-graph shape-argument validation (reference:
    python/paddle/base/data_feeder.py:227).  Accepts list/tuple of ints or a
    1-D integer Tensor; raises TypeError otherwise."""
    from paddle_tpu._core.tensor import Tensor

    if isinstance(shape, Tensor):
        if str(shape.dtype).split(".")[-1] not in expected_tensor_dtype:
            raise TypeError(f"{op_name}: shape tensor dtype must be one of {expected_tensor_dtype}")
        return
    if not isinstance(shape, expected_shape_type):
        raise TypeError(f"{op_name}: shape must be {expected_shape_type}, got {type(shape)}")
    for item in shape:
        if isinstance(item, Tensor):
            continue
        if not isinstance(item, expected_element_type) or isinstance(item, bool):
            raise TypeError(f"{op_name}: shape element must be {expected_element_type}, got {type(item)}")


def disable_signal_handler():
    """API-compat: the reference uninstalls its C++ fault signal handlers
    (paddle/fluid/platform/init.cc).  This runtime installs none — XLA/PJRT
    handle their own — so there is nothing to disable."""
    return None


class LazyGuard:
    """Deferred parameter materialization (reference:
    python/paddle/nn/initializer/lazy_init.py:91 LazyGuard).

    The reference builds layers with zero-memory params and materializes via
    param.initialize().  TPU-native equivalent: inside the guard all arrays
    (including initializer outputs) are created on the HOST cpu backend —
    no HBM is touched — and Parameter.initialize() (or the first compiled
    step, which device_puts its donated state) moves them to the chip,
    optionally through a sharding.  This is the host-init + shard-on-entry
    pattern large-model JAX code uses.
    """

    def __init__(self):
        self._ctx = None

    def __enter__(self):
        import jax

        self._ctx = jax.default_device(jax.devices("cpu")[0])
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        ctx, self._ctx = self._ctx, None
        return ctx.__exit__(*exc)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False, default_initializer=None):
    """Standalone parameter factory (reference: paddle.create_parameter,
    python/paddle/tensor/creation.py)."""
    from paddle_tpu._core.dtype import to_jax_dtype
    from paddle_tpu._core.tensor import Parameter
    from paddle_tpu.nn import initializer as I
    from paddle_tpu.nn.layer.layers import ParamAttr

    attr = ParamAttr._to_attr(attr)
    # precedence: explicit ParamAttr > set_global_initializer > layer default
    init = attr.initializer or I._default_init(is_bias) or default_initializer
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    value = init._init_value(tuple(int(s) for s in shape), to_jax_dtype(dtype))
    p = Parameter(value, trainable=attr.trainable, name=name or attr.name or "")
    p.optimize_attr["learning_rate"] = attr.learning_rate
    p.regularizer = attr.regularizer
    p.need_clip = attr.need_clip
    return p


__all__ += ["LazyGuard", "create_parameter"]
