"""Op registry + codegen.

Reference: the single YAML op registry feeding four generators
(paddle/phi/api/yaml/ops.yaml + generator/api_gen.py, eager_gen.py,
python_c_gen.py, op_gen.py) — SURVEY.md:35 calls it the most load-bearing
design idea.

TPU-native redesign: the registry's C++ outputs (kernel dispatch, generated
GradNodes, pybind wrappers, PIR dialect) are all subsumed — jnp IS the
kernel library, jax.vjp the grad codegen, the apply() funnel the dual
eager/static dispatch.  What REMAINS load-bearing is the metadata and the
python-surface codegen, built here:

- `OpInfo` per public op: module, signature, category, AMP class (from the
  dispatcher's white/black lists), dynamic-shape flag (ops that raise
  DynamicShapeError under tracing), Tensor-method availability.
- `build_registry()` introspects the live op surface (the schemas stay in
  sync with the code by construction — no drift between YAML and impl).
- Codegen consumers:
  * `generate_inplace_variants()` emits the `op_` in-place API tier
    (reference: generated inplace ad_funcs) — bind-back wrappers over the
    functional ops, installed as module fns + Tensor methods;
  * `generate_markdown()` renders the op table (docs artifact).
Tests assert registry/app surface consistency (tests/test_op_registry.py).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field

__all__ = [
    "OpInfo",
    "build_registry",
    "get_op_info",
    "all_ops",
    "generate_inplace_variants",
    "generate_markdown",
]


@dataclass
class OpInfo:
    name: str
    module: str
    category: str
    signature: str
    amp_class: str = "neutral"  # white | black | neutral
    dynamic_shape: bool = False  # raises DynamicShapeError under tracing
    has_tensor_method: bool = False
    inplace_variant: str | None = None
    doc: str = ""


# Ops whose output shape depends on data (documented DynamicShapeError
# under tracing — kept in sync by tests/test_op_traceability.py)
_DYNAMIC_SHAPE_OPS = {
    "masked_select", "nonzero", "unique", "unique_consecutive", "bincount",
    "eig", "eigvals",
}

_registry: dict[str, OpInfo] | None = None

# op-factory plumbing that lives in the op modules but is not itself a
# public op (would pollute the registry's op counts and docs)
_NOT_OPS = {"apply", "binary", "unary", "ensure_tensor", "to_jax_dtype"}


def _op_modules():
    import importlib

    from paddle_tpu.tensor import (
        creation, linalg, logic, manipulation, math, random, search, stat,
    )

    # NOTE: `from paddle_tpu.tensor import einsum` would bind the einsum
    # FUNCTION (re-exported by the package __init__), not the module —
    # import it by path so its ops register.
    einsum_mod = importlib.import_module("paddle_tpu.tensor.einsum")
    return {
        "math": math, "manipulation": manipulation, "linalg": linalg,
        "logic": logic, "search": search, "stat": stat, "creation": creation,
        "random": random, "einsum": einsum_mod,
    }


def build_registry() -> dict[str, OpInfo]:
    global _registry
    if _registry is not None:
        return _registry
    from paddle_tpu import amp
    from paddle_tpu._core.tensor import Tensor

    white, black = amp.white_list(), amp.black_list()
    reg: dict[str, OpInfo] = {}
    for cat, mod in _op_modules().items():
        for name in dir(mod):
            if name.startswith("_") or name in _NOT_OPS:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            # factory-made ops (binary()/unary() helpers) carry the helper's
            # module; accept anything defined inside the framework
            if not getattr(fn, "__module__", "").startswith("paddle_tpu"):
                continue
            try:
                sig = str(inspect.signature(fn))
            except (TypeError, ValueError):
                sig = "(...)"
            if name in reg:
                continue
            reg[name] = OpInfo(
                name=name,
                module=mod.__name__,
                category=cat,
                signature=sig,
                amp_class="white" if name in white else ("black" if name in black else "neutral"),
                dynamic_shape=name in _DYNAMIC_SHAPE_OPS,
                has_tensor_method=hasattr(Tensor, name),
                inplace_variant=name + "_" if hasattr(mod, name + "_") else None,
                doc=(fn.__doc__ or "").strip().splitlines()[0] if fn.__doc__ else "",
            )
    _registry = reg
    return reg


def get_op_info(name: str) -> OpInfo:
    reg = build_registry()
    if name not in reg:
        raise KeyError(f"unknown op {name!r}")
    return reg[name]


def all_ops() -> list[str]:
    return sorted(build_registry())


# ------------------------------------------------------------------ codegen

# The in-place tier (reference: inplace ad_funcs generated from the
# `inplace:` YAML field).  Each entry maps to its functional base op.
_INPLACE_BASES = [
    "add", "subtract", "multiply", "divide", "remainder", "clip", "scale",
    "exp", "sqrt", "rsqrt", "reciprocal", "floor", "ceil", "round", "tanh",
    "abs", "neg",
    # full reference in-place tier (python/paddle/__init__.py `*_` exports)
    "acos", "asin", "atan", "atanh", "asinh", "acosh", "cos", "cosh", "sin",
    "sinh", "tan", "erf", "expm1", "log", "log2", "log10", "log1p", "logit",
    "lgamma", "digamma", "multigammaln", "polygamma", "i0", "frac", "trunc",
    "square", "nan_to_num", "hypot", "ldexp", "gcd", "lcm", "addmm",
    "cumsum", "cumprod", "renorm", "index_fill", "masked_scatter",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "floor_divide", "floor_mod", "mod", "tril", "triu",
    "pow", "lerp", "fill_diagonal", "put_along_axis", "index_add",
    "erfinv", "flatten", "index_put", "sigmoid",
]


def _make_inplace(base_fn, name):
    def inplace(x, *args, **kwargs):
        from paddle_tpu.tensor._ops_common import inplace_from

        return inplace_from(x, base_fn, *args, **kwargs)

    inplace.__name__ = name
    inplace.__qualname__ = name
    inplace.__doc__ = (
        f"In-place variant of `{base_fn.__name__}` (generated by the op "
        f"registry; functional under the hood — XLA buffer donation makes "
        f"the compiled form genuinely in-place)."
    )
    return inplace


def generate_inplace_variants() -> list[str]:
    """Install `op_` functions + Tensor methods for the in-place tier.

    Returns the generated names.  Idempotent; existing hand-written
    variants are left untouched.
    """
    from paddle_tpu._core.tensor import Tensor

    generated = []
    mods = _op_modules()
    for base in _INPLACE_BASES:
        target = None
        for mod in mods.values():
            if hasattr(mod, base):
                target = mod
                break
        if target is None:
            continue
        name = base + "_"
        if not hasattr(target, name):
            fn = _make_inplace(getattr(target, base), name)
            setattr(target, name, fn)
            generated.append(name)
        if not hasattr(Tensor, name):
            setattr(Tensor, name, getattr(target, name))
    global _registry
    _registry = None  # registry reflects the new surface on next build
    return generated


def generate_markdown() -> str:
    """Render the registry as a markdown table (docs artifact)."""
    lines = [
        "| op | category | amp | traced | method | inplace |",
        "|---|---|---|---|---|---|",
    ]
    for name in all_ops():
        i = get_op_info(name)
        lines.append(
            f"| {i.name} | {i.category} | {i.amp_class} | "
            f"{'dynamic-shape (eager only)' if i.dynamic_shape else 'yes'} | "
            f"{'yes' if i.has_tensor_method else ''} | {i.inplace_variant or ''} |"
        )
    return "\n".join(lines)
