"""Op registry + codegen.

Reference: the single YAML op registry feeding four generators
(paddle/phi/api/yaml/ops.yaml + generator/api_gen.py, eager_gen.py,
python_c_gen.py, op_gen.py) — SURVEY.md:35 calls it the most load-bearing
design idea.

TPU-native redesign: the registry's C++ outputs (kernel dispatch, generated
GradNodes, pybind wrappers, PIR dialect) are all subsumed — jnp IS the
kernel library, jax.vjp the grad codegen, the apply() funnel the dual
eager/static dispatch.  What REMAINS load-bearing is the metadata and the
python-surface codegen, built here:

- `OpInfo` per public op: module, signature, category, AMP class (from the
  dispatcher's white/black lists), dynamic-shape flag (ops that raise
  DynamicShapeError under tracing), Tensor-method availability.
- `build_registry()` introspects the live op surface (the schemas stay in
  sync with the code by construction — no drift between YAML and impl).
- Codegen consumers:
  * `generate_inplace_variants()` emits the `op_` in-place API tier
    (reference: generated inplace ad_funcs) — bind-back wrappers over the
    functional ops, installed as module fns + Tensor methods;
  * `generate_markdown()` renders the op table (docs artifact).
Tests assert registry/app surface consistency (tests/test_op_registry.py).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field

__all__ = [
    "OpInfo",
    "build_registry",
    "get_op_info",
    "all_ops",
    "generate_inplace_variants",
    "generate_markdown",
    "applied_op_names",
    "known_op_types",
    "resolve_op_type",
    "register_op_type",
    "side_effect_op_types",
]


@dataclass
class OpInfo:
    name: str
    module: str
    category: str
    signature: str
    amp_class: str = "neutral"  # white | black | neutral
    dynamic_shape: bool = False  # raises DynamicShapeError under tracing
    has_tensor_method: bool = False
    inplace_variant: str | None = None
    doc: str = ""


# Ops whose output shape depends on data (documented DynamicShapeError
# under tracing — kept in sync by tests/test_op_traceability.py)
_DYNAMIC_SHAPE_OPS = {
    "masked_select", "nonzero", "unique", "unique_consecutive", "bincount",
    "eig", "eigvals",
}

_registry: dict[str, OpInfo] | None = None

# op-factory plumbing that lives in the op modules but is not itself a
# public op (would pollute the registry's op counts and docs)
_NOT_OPS = {"apply", "binary", "unary", "ensure_tensor", "to_jax_dtype"}


def _op_modules():
    import importlib

    from paddle_tpu.tensor import (
        creation, linalg, logic, manipulation, math, random, search, stat,
    )

    # NOTE: `from paddle_tpu.tensor import einsum` would bind the einsum
    # FUNCTION (re-exported by the package __init__), not the module —
    # import it by path so its ops register.
    einsum_mod = importlib.import_module("paddle_tpu.tensor.einsum")
    return {
        "math": math, "manipulation": manipulation, "linalg": linalg,
        "logic": logic, "search": search, "stat": stat, "creation": creation,
        "random": random, "einsum": einsum_mod,
    }


def build_registry() -> dict[str, OpInfo]:
    global _registry
    if _registry is not None:
        return _registry
    from paddle_tpu import amp
    from paddle_tpu._core.tensor import Tensor

    white, black = amp.white_list(), amp.black_list()
    reg: dict[str, OpInfo] = {}
    for cat, mod in _op_modules().items():
        for name in dir(mod):
            if name.startswith("_") or name in _NOT_OPS:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            # factory-made ops (binary()/unary() helpers) carry the helper's
            # module; accept anything defined inside the framework
            if not getattr(fn, "__module__", "").startswith("paddle_tpu"):
                continue
            try:
                sig = str(inspect.signature(fn))
            except (TypeError, ValueError):
                sig = "(...)"
            if name in reg:
                continue
            reg[name] = OpInfo(
                name=name,
                module=mod.__name__,
                category=cat,
                signature=sig,
                amp_class="white" if name in white else ("black" if name in black else "neutral"),
                dynamic_shape=name in _DYNAMIC_SHAPE_OPS,
                has_tensor_method=hasattr(Tensor, name),
                inplace_variant=name + "_" if hasattr(mod, name + "_") else None,
                doc=(fn.__doc__ or "").strip().splitlines()[0] if fn.__doc__ else "",
            )
    _registry = reg
    return reg


def get_op_info(name: str) -> OpInfo:
    reg = build_registry()
    if name not in reg:
        raise KeyError(f"unknown op {name!r}")
    return reg[name]


def all_ops() -> list[str]:
    return sorted(build_registry())


# ------------------------------------------------------- op-type resolution
#
# A captured Program's Operator.type space is wider than the public-op
# registry: the apply() funnel records nn-functional / loss / sequence ops
# under their own names, passes append namespaced super-ops
# ("fp16::matmul", "wq::linear", "gradient_merge::optimizer_update"), and
# decomposition emits raw jax primitive names.  The verifier
# (static/verify.py) resolves every recorded type through here so an op
# rename — which would silently stop rewrite patterns from matching — is a
# mechanical error instead.

# Structural op types emitted by the IR machinery itself, not the funnel.
_STRUCTURAL_OP_TYPES = {
    "grad",              # static/autodiff.py value_and_grad super-op
    "share_loss",        # loss-vid re-bind alias (autodiff)
    "optimizer_update",  # optimizer/optimizer.py static step super-op
    "segment",           # recompute::segment (distributed program rewrite)
    "accumulate",        # gradient_merge::accumulate
}

# Types produced by the Pallas substitution passes (static/rewrite.py).
_PASS_OP_TYPES = {
    "flash_attention",
    "fused_rms_norm",
    "fused_layer_norm",
    "swiglu",
    "matmul_epilogue",
    "add_rms_norm",
    "add_layer_norm",
}

_EXTRA_OP_TYPES: set[str] = set()

_applied_names: frozenset[str] | None = None
_primitive_names: frozenset[str] | None = None


def register_op_type(name: str):
    """Declare an extension op type as resolvable (plugins / custom passes)."""
    global _known_types_cache
    _EXTRA_OP_TYPES.add(str(name))
    _known_types_cache = None
    return name


def applied_op_names() -> frozenset[str]:
    """Every op name the package passes to the apply()/record() funnel as a
    string literal — scanned from source once and cached.  This is the full
    legitimate Operator.type surface beyond the public-op registry; an op
    rename changes this set, so pattern references to the old name become
    detectable (tests/test_api_surface.py lint)."""
    global _applied_names
    if _applied_names is None:
        import pathlib
        import re

        import paddle_tpu

        # apply()/record() direct literals plus the unary()/binary() op
        # factories (tensor/_ops_common.py), whose first arg IS the op id
        pat = re.compile(
            r"""\b(?:apply|record|unary|binary)\(\s*['"]([A-Za-z0-9_]+)['"]""")
        names: set[str] = set()
        pkg = pathlib.Path(paddle_tpu.__file__).parent
        for p in pkg.rglob("*.py"):
            try:
                names.update(pat.findall(p.read_text()))
            except OSError:
                continue
        _applied_names = frozenset(names)
    return _applied_names


def _jax_primitive_names() -> frozenset[str]:
    """jax primitive names (decomposition emits one Operator per eqn)."""
    global _primitive_names
    if _primitive_names is None:
        names: set[str] = set()
        try:
            from jax.extend import core as _xcore

            prims = _xcore.primitives
            for attr in dir(prims):
                if attr.endswith("_p"):
                    prim = getattr(prims, attr)
                    name = getattr(prim, "name", None)
                    if isinstance(name, str):
                        names.add(name)
        except Exception:
            pass
        _primitive_names = frozenset(names)
    return _primitive_names


_known_types_cache: frozenset[str] | None = None


def known_op_types() -> frozenset[str]:
    """Union of every resolvable base op type (no namespaces); cached —
    the verifier resolves every op of every swept program through this."""
    global _known_types_cache
    if _known_types_cache is None:
        _known_types_cache = frozenset(build_registry()) | applied_op_names() \
            | _STRUCTURAL_OP_TYPES | _PASS_OP_TYPES | _EXTRA_OP_TYPES \
            | _jax_primitive_names()
    return _known_types_cache


def base_op_type(type_: str) -> str:
    """Strip pass-inserted namespaces ("wq::fp16::matmul" -> "matmul").

    The single definition of the namespace convention — the rewrite
    patterns, DCE's side-effect check, and the verifier all anchor on it
    and must agree."""
    return type_.rsplit("::", 1)[-1]


def resolve_op_type(type_: str) -> bool:
    """True when a recorded Operator.type resolves to a known op.

    Strips pass namespaces ("wq::fp16::matmul" -> "matmul"), accepts the
    generated vpu_chain_<N> kernels and eager "<op>_grad" tape nodes."""
    base = base_op_type(type_)
    if base in known_op_types():
        return True
    if base.startswith("vpu_chain_") and base[len("vpu_chain_"):].isdigit():
        return True
    if base.startswith("sched_chain_") and base[len("sched_chain_"):].isdigit():
        return True  # schedule-searched subgraph kernels (static/schedule_search.py)
    if base.endswith("_grad") and base[: -len("_grad")] in known_op_types():
        return True
    return False


# Op types with host- or peer-visible effects: eliminating them changes
# behavior beyond their data outputs (RNG stream consumption, printing,
# user callbacks, a rank's collective participation), so DCE must keep
# them even when no fetch reaches their outputs.
_SIDE_EFFECT_EXTRA = {
    "seed", "print", "py_func", "ps_pull_sparse",
    "dropout", "alpha_dropout", "rrelu", "gumbel_softmax",
    "all_reduce", "all_gather", "send", "recv", "barrier",
}

_side_effect_cache: frozenset[str] | None = None


def side_effect_op_types() -> frozenset[str]:
    """Base op types DeadCodeEliminationPass must never eliminate: the
    generated in-place tier (`op_` names), the RNG tier (registry category
    "random"), and the explicit host/collective-effect set."""
    global _side_effect_cache
    if _side_effect_cache is None:
        reg = build_registry()
        names = {n for n in reg if n.endswith("_")}
        names.update(n for n, i in reg.items() if i.category == "random")
        names.update(_SIDE_EFFECT_EXTRA)
        _side_effect_cache = frozenset(names)
    return _side_effect_cache


# ------------------------------------------------------------------ codegen

# The in-place tier (reference: inplace ad_funcs generated from the
# `inplace:` YAML field).  Each entry maps to its functional base op.
_INPLACE_BASES = [
    "add", "subtract", "multiply", "divide", "remainder", "clip", "scale",
    "exp", "sqrt", "rsqrt", "reciprocal", "floor", "ceil", "round", "tanh",
    "abs", "neg",
    # full reference in-place tier (python/paddle/__init__.py `*_` exports)
    "acos", "asin", "atan", "atanh", "asinh", "acosh", "cos", "cosh", "sin",
    "sinh", "tan", "erf", "expm1", "log", "log2", "log10", "log1p", "logit",
    "lgamma", "digamma", "multigammaln", "polygamma", "i0", "frac", "trunc",
    "square", "nan_to_num", "hypot", "ldexp", "gcd", "lcm", "addmm",
    "cumsum", "cumprod", "renorm", "index_fill", "masked_scatter",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "floor_divide", "floor_mod", "mod", "tril", "triu",
    "pow", "lerp", "fill_diagonal", "put_along_axis", "index_add",
    "erfinv", "flatten", "index_put", "sigmoid",
]


def _make_inplace(base_fn, name):
    def inplace(x, *args, **kwargs):
        from paddle_tpu.tensor._ops_common import inplace_from

        return inplace_from(x, base_fn, *args, **kwargs)

    inplace.__name__ = name
    inplace.__qualname__ = name
    inplace.__doc__ = (
        f"In-place variant of `{base_fn.__name__}` (generated by the op "
        f"registry; functional under the hood — XLA buffer donation makes "
        f"the compiled form genuinely in-place)."
    )
    return inplace


def generate_inplace_variants() -> list[str]:
    """Install `op_` functions + Tensor methods for the in-place tier.

    Returns the generated names.  Idempotent; existing hand-written
    variants are left untouched.
    """
    from paddle_tpu._core.tensor import Tensor

    generated = []
    mods = _op_modules()
    for base in _INPLACE_BASES:
        target = None
        for mod in mods.values():
            if hasattr(mod, base):
                target = mod
                break
        if target is None:
            continue
        name = base + "_"
        if not hasattr(target, name):
            fn = _make_inplace(getattr(target, base), name)
            setattr(target, name, fn)
            generated.append(name)
        if not hasattr(Tensor, name):
            setattr(Tensor, name, getattr(target, name))
    global _registry, _side_effect_cache, _known_types_cache
    _registry = None  # registry reflects the new surface on next build
    _side_effect_cache = None
    _known_types_cache = None
    return generated


def generate_markdown() -> str:
    """Render the registry as a markdown table (docs artifact)."""
    lines = [
        "| op | category | amp | traced | method | inplace |",
        "|---|---|---|---|---|---|",
    ]
    for name in all_ops():
        i = get_op_info(name)
        lines.append(
            f"| {i.name} | {i.category} | {i.amp_class} | "
            f"{'dynamic-shape (eager only)' if i.dynamic_shape else 'yes'} | "
            f"{'yes' if i.has_tensor_method else ''} | {i.inplace_variant or ''} |"
        )
    return "\n".join(lines)
