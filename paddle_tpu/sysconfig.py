"""Build configuration queries (reference: python/paddle/sysconfig.py)."""

from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]


def get_include() -> str:
    """Directory containing the framework's C headers (the custom-op
    extension tier's include root — see utils/cpp_extension)."""
    root = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(root, "utils", "cpp_extension")  # paddle_tpu_ext.h


def get_lib() -> str:
    """Directory containing the framework's native libraries (the build
    cache _native compiles libpaddle_tpu_native.so into)."""
    return os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
