"""paddle.audio.backends equivalent (reference:
python/paddle/audio/backends/ — wave_backend.py load/save/info over the
stdlib wave module; the reference likewise falls back to a pure wave
backend when paddleaudio is absent)."""

from __future__ import annotations

import wave

import numpy as np

from paddle_tpu._core.tensor import Tensor

__all__ = ["load", "save", "info", "list_available_backends", "get_current_backend", "set_backend"]


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels, bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name != "wave_backend":
        raise NotImplementedError("only wave_backend is available")


def info(filepath):
    """reference audio/backends/wave_backend.py info."""
    with wave.open(filepath, "rb") as f:
        return AudioInfo(
            sample_rate=f.getframerate(),
            num_samples=f.getnframes(),
            num_channels=f.getnchannels(),
            bits_per_sample=f.getsampwidth() * 8,
        )


def load(filepath, frame_offset=0, num_frames=-1, normalize=True, channels_first=True):
    """Load wav → (Tensor [C, T] float32 in [-1,1], sample_rate)
    (reference wave_backend.py load)."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dtypes = {1: np.uint8, 2: np.int16, 4: np.int32}
    if width not in dtypes:
        raise NotImplementedError(
            f"{8 * width}-bit PCM wav is not supported (8/16/32-bit only)"
        )
    dtype = dtypes[width]
    data = np.frombuffer(raw, dtype=dtype).reshape(-1, nch)
    if width == 1:
        data = data.astype(np.int16) - 128  # 8-bit wav is unsigned
        scale = 1 << 7
    else:
        scale = 1 << (8 * width - 1)
    if normalize:
        out = (data.astype(np.float32)) / scale
    else:
        out = data
    out = out.T if channels_first else out
    return Tensor(np.ascontiguousarray(out)), sr


def save(filepath, src, sample_rate, channels_first=True, encoding="PCM_16", bits_per_sample=16):
    """Save float waveform in [-1,1] to PCM wav (reference
    wave_backend.py save)."""
    data = np.asarray(src._value if isinstance(src, Tensor) else src)
    if channels_first:
        data = data.T
    if data.ndim == 1:
        data = data[:, None]
    width = bits_per_sample // 8
    if width != 2:
        raise NotImplementedError("only 16-bit PCM save is supported")
    scaled = np.clip(data, -1.0, 1.0) * ((1 << 15) - 1)
    pcm = scaled.astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(width)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
