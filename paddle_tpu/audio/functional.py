"""paddle.audio.functional equivalent (reference:
python/paddle/audio/functional/functional.py + window.py — 8 exports).
Pure jnp feature math (slaney + htk mel scales, matching librosa
conventions like the reference)."""

from __future__ import annotations

import math

import jax.numpy as jnp

from paddle_tpu._core.dtype import to_jax_dtype
from paddle_tpu._core.tensor import Tensor

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "create_dct", "power_to_db", "get_window",
]


def _v(x):
    return x._value if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk=False):
    """reference audio/functional/functional.py:22"""
    f = _v(freq)
    fa = jnp.asarray(f, jnp.float32) if not isinstance(f, jnp.ndarray) else f
    if htk:
        out = 2595.0 * jnp.log10(1.0 + fa / 700.0)
    else:
        f_sp = 200.0 / 3
        mels = fa / f_sp
        min_log_hz = 1000.0
        min_log_mel = min_log_hz / f_sp
        logstep = math.log(6.4) / 27.0
        log_t = min_log_mel + jnp.log(fa / min_log_hz + 1e-10) / logstep
        out = jnp.where(fa > min_log_hz, log_t, mels)
    if isinstance(freq, Tensor):
        return Tensor(out)
    return float(out) if out.ndim == 0 else Tensor(out)


def mel_to_hz(mel, htk=False):
    """reference audio/functional/functional.py:78"""
    m = _v(mel)
    ma = jnp.asarray(m, jnp.float32) if not isinstance(m, jnp.ndarray) else m
    if htk:
        out = 700.0 * (jnp.power(10.0, ma / 2595.0) - 1.0)
    else:
        f_sp = 200.0 / 3
        freqs = ma * f_sp
        min_log_hz = 1000.0
        min_log_mel = min_log_hz / f_sp
        logstep = math.log(6.4) / 27.0
        log_t = min_log_hz * jnp.exp(logstep * (ma - min_log_mel))
        out = jnp.where(ma > min_log_mel, log_t, freqs)
    if isinstance(mel, Tensor):
        return Tensor(out)
    return float(out) if out.ndim == 0 else Tensor(out)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False, dtype="float32"):
    """reference audio/functional/functional.py:131"""
    min_mel = _v(hz_to_mel(f_min, htk))
    max_mel = _v(hz_to_mel(f_max, htk))
    mels = jnp.linspace(min_mel, max_mel, n_mels)
    return Tensor(jnp.asarray(_v(mel_to_hz(Tensor(mels), htk)), to_jax_dtype(dtype)))


def fft_frequencies(sr, n_fft, dtype="float32"):
    """reference audio/functional/functional.py:163"""
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2, dtype=to_jax_dtype(dtype)))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Mel filterbank [n_mels, 1+n_fft//2] (reference functional.py:185)."""
    if f_max is None:
        f_max = sr / 2
    fftfreqs = _v(fft_frequencies(sr, n_fft, dtype))
    mel_f = _v(mel_frequencies(n_mels + 2, f_min, f_max, htk, dtype))
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]  # [n_mels+2, n_freq]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2 : n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights.astype(to_jax_dtype(dtype)))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (reference functional.py:252)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k[None, :]) * 2.0
    if norm == "ortho":
        dct = dct.at[:, 0].multiply(math.sqrt(1.0 / (4 * n_mels)))
        dct = dct.at[:, 1:].multiply(math.sqrt(1.0 / (2 * n_mels)))
    else:
        dct = dct / 2  # match torchaudio's norm=None scaling used by reference
    return Tensor(dct.astype(to_jax_dtype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """Power spectrogram → dB (reference functional.py:285)."""
    x = _v(spect)
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
    log_spec = log_spec - 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec)


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Window function by name (reference audio/functional/window.py:318):
    hamming, hann, blackman, bartlett, kaiser, gaussian, exponential,
    taylor, bohman, nuttall, cosine, tukey, triang."""
    if isinstance(window, tuple):
        name, *args = window
    else:
        name, args = window, []
    n = win_length + 1 if fftbins else win_length

    t = jnp.arange(n, dtype=jnp.float32)
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * t / (n - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * t / (n - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * jnp.cos(2 * math.pi * t / (n - 1))
             + 0.08 * jnp.cos(4 * math.pi * t / (n - 1)))
    elif name == "bartlett":
        w = 1 - jnp.abs(2 * t / (n - 1) - 1)
    elif name == "nuttall":
        a = (0.3635819, 0.4891775, 0.1365995, 0.0106411)
        fac = 2 * math.pi * t / (n - 1)
        w = a[0] - a[1] * jnp.cos(fac) + a[2] * jnp.cos(2 * fac) - a[3] * jnp.cos(3 * fac)
    elif name == "bohman":
        fac = jnp.abs(2 * t / (n - 1) - 1)
        w = (1 - fac) * jnp.cos(math.pi * fac) + jnp.sin(math.pi * fac) / math.pi
        w = jnp.where(fac < 1, w, 0)
    elif name == "cosine":
        w = jnp.sin(math.pi / n * (t + 0.5))
    elif name == "triang":
        if n % 2 == 0:
            w = (2 * t + 1) / n
            w = jnp.where(t < n // 2, w, 2 - (2 * t + 1) / n)
        else:
            w = 2 * (t + 1) / (n + 1)
            w = jnp.where(t < n // 2, w, 2 - 2 * (t + 1) / (n + 1))
    elif name == "kaiser":
        beta = args[0] if args else 12.0
        from jax.scipy.special import i0

        alpha = (n - 1) / 2.0
        w = i0(beta * jnp.sqrt(jnp.clip(1 - ((t - alpha) / alpha) ** 2, 0, 1))) / i0(
            jnp.asarray(beta, jnp.float32)
        )
    elif name == "gaussian":
        std = args[0] if args else 1.0
        w = jnp.exp(-0.5 * ((t - (n - 1) / 2) / std) ** 2)
    elif name == "exponential":
        center = args[0] if args else None
        tau = args[1] if len(args) > 1 else 1.0
        c = (n - 1) / 2 if center is None else center
        w = jnp.exp(-jnp.abs(t - c) / tau)
    elif name == "tukey":
        alpha = args[0] if args else 0.5
        if alpha <= 0:
            w = jnp.ones(n)
        elif alpha >= 1:
            w = 0.5 - 0.5 * jnp.cos(2 * math.pi * t / (n - 1))
        else:
            edge = alpha * (n - 1) / 2
            w = jnp.where(
                t < edge,
                0.5 * (1 + jnp.cos(math.pi * (2 * t / (alpha * (n - 1)) - 1))),
                jnp.where(
                    t <= (n - 1) * (1 - alpha / 2),
                    1.0,
                    0.5 * (1 + jnp.cos(math.pi * (2 * t / (alpha * (n - 1)) - 2 / alpha + 1))),
                ),
            )
    else:
        raise ValueError(f"unsupported window: {name!r}")
    if fftbins:
        w = w[:-1]
    return Tensor(w.astype(to_jax_dtype(dtype)))
