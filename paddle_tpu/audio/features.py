"""paddle.audio.features equivalent (reference:
python/paddle/audio/features/layers.py — Spectrogram, MelSpectrogram,
LogMelSpectrogram, MFCC layers composing signal.stft with the functional
feature math; the whole pipeline is jnp and jit-fusible)."""

from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer
from paddle_tpu import signal

from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """reference audio/features/layers.py:24"""

    def __init__(self, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = F.get_window(window, self.win_length, True, dtype)

    def forward(self, x):
        spec = signal.stft(
            x, self.n_fft, self.hop_length, self.win_length,
            window=self.fft_window, center=self.center, pad_mode=self.pad_mode,
        )
        return Tensor(jnp.abs(spec._value) ** self.power)


class MelSpectrogram(Layer):
    """reference audio/features/layers.py:106"""

    def __init__(self, sr=22050, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype)
        self.fbank_matrix = F.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype
        )

    def forward(self, x):
        spec = self._spectrogram(x)
        mel = jnp.matmul(self.fbank_matrix._value, spec._value)
        return Tensor(mel)


class LogMelSpectrogram(Layer):
    """reference audio/features/layers.py:206"""

    def __init__(self, sr=22050, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype
        )
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return F.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    """reference audio/features/layers.py:309"""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=512,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode,
            n_mels, f_min, f_max, htk, norm, ref_value, amin, top_db, dtype
        )
        self.dct_matrix = F.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)._value  # [..., n_mels, n_frames]
        mfcc = jnp.einsum("mk,...mt->...kt", self.dct_matrix._value, logmel)
        return Tensor(mfcc)
