"""paddle.audio equivalent (reference: python/paddle/audio/__init__.py —
functional, features, backends (wave IO), datasets (ESC50, TESS))."""

from . import features, functional  # noqa: F401
from .backends import info, load, save  # noqa: F401
from . import backends, datasets  # noqa: F401

__all__ = ["functional", "features", "backends", "datasets", "load", "save", "info"]
