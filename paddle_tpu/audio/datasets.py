"""paddle.audio.datasets equivalent (reference:
python/paddle/audio/datasets/ — AudioClassificationDataset base, ESC50,
TESS).  Downloads are impossible in a zero-egress environment, so datasets
load from a local `data_dir`; the archive layout matches the reference's
extracted download."""

from __future__ import annotations

import csv
import os

import numpy as np

from paddle_tpu.io import Dataset

from . import backends, features

__all__ = ["AudioClassificationDataset", "ESC50", "TESS"]


class AudioClassificationDataset(Dataset):
    """reference audio/datasets/dataset.py:24 — wav files + labels with an
    optional on-the-fly feature transform."""

    _feat_types = ("raw", "melspectrogram", "mfcc", "logmelspectrogram", "spectrogram")

    def __init__(self, files, labels, feat_type="raw", sample_rate=None, archive=None, **kwargs):
        if feat_type not in self._feat_types:
            raise ValueError(f"feat_type must be one of {self._feat_types}")
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = kwargs
        self._feat_layer = None  # built once on first use (per fixed sr)
        self._feat_sr = None

    def _feature(self, waveform, sr):
        if self.feat_type == "raw":
            return waveform
        if self._feat_layer is None or self._feat_sr != sr:
            layer_cls = {
                "melspectrogram": features.MelSpectrogram,
                "logmelspectrogram": features.LogMelSpectrogram,
                "mfcc": features.MFCC,
                "spectrogram": features.Spectrogram,
            }[self.feat_type]
            cfg = dict(self.feat_config)
            if self.feat_type != "spectrogram":
                cfg.setdefault("sr", sr)
            self._feat_layer = layer_cls(**cfg)
            self._feat_sr = sr
        return self._feat_layer(waveform)

    def __getitem__(self, idx):
        wav, sr = backends.load(self.files[idx])
        mono = wav._value[0]
        from paddle_tpu._core.tensor import Tensor

        feat = self._feature(Tensor(mono), sr)
        return np.asarray(feat._value), np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.files)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py:26).
    Expects the extracted archive at data_dir (meta/esc50.csv + audio/)."""

    def __init__(self, mode="train", split=1, feat_type="raw", data_dir=None, **kwargs):
        if data_dir is None or not os.path.isdir(data_dir):
            raise RuntimeError(
                "ESC50 requires a local copy (no network): pass data_dir "
                "pointing at the extracted ESC-50 archive"
            )
        meta = os.path.join(data_dir, "meta", "esc50.csv")
        files, labels = [], []
        with open(meta, newline="") as f:
            for row in csv.DictReader(f):
                in_fold = int(row["fold"]) == split
                if (mode == "train") != in_fold:  # train: folds != split
                    files.append(os.path.join(data_dir, "audio", row["filename"]))
                    labels.append(int(row["target"]))
        super().__init__(files, labels, feat_type, **kwargs)


class TESS(AudioClassificationDataset):
    """TESS emotional speech (reference audio/datasets/tess.py:26).
    Expects extracted wavs under data_dir, emotion label in the filename."""

    emotions = ("angry", "disgust", "fear", "happy", "neutral", "ps", "sad")

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw", data_dir=None, **kwargs):
        if data_dir is None or not os.path.isdir(data_dir):
            raise RuntimeError(
                "TESS requires a local copy (no network): pass data_dir "
                "pointing at the extracted TESS archive"
            )
        files, labels = [], []
        all_wavs = sorted(
            os.path.join(root, f)
            for root, _, fs in os.walk(data_dir)
            for f in fs
            if f.lower().endswith(".wav")
        )
        for i, path in enumerate(all_wavs):
            emo = os.path.splitext(os.path.basename(path))[0].split("_")[-1].lower()
            if emo not in self.emotions:
                continue
            fold = i % n_folds + 1
            if (mode == "train") != (fold == split):
                files.append(path)
                labels.append(self.emotions.index(emo))
        super().__init__(files, labels, feat_type, **kwargs)
