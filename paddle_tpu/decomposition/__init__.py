"""paddle.decomposition equivalent (reference:
python/paddle/decomposition/decomp.py:192 `decompose` — rewrite composite
ops in a program into the primitive set, using the composite rules in
paddle/fluid/primitive/composite/ and generated VJP rules).

TPU-native redesign: the primitive set IS jax's primitive set.  Each
Operator in a static Program carries a traceable `fn`; `decompose` traces
it with jax.make_jaxpr, inlines higher-order primitives (pjit /
custom_jvp / custom_vjp / remat), and splices one Operator per remaining
jaxpr equation back into the block — preserving the op's output Variables
so feeds/fetches/writes stay valid.  Composite ops like softmax or
layer_norm therefore decompose into exp/div/reduce/… exactly as the
reference's composite rules would, but mechanically and for every op."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.static.program import Operator, Program, Variable, suspend_capture

__all__ = ["decompose", "decompose_op", "is_primitive_op"]

# higher-order primitives whose inner jaxpr we inline
_INLINE = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
    "remat2": "jaxpr",
    "checkpoint": "jaxpr",
}


def is_primitive_op(program, op) -> bool:
    """True if the op's fn traces to a single first-order equation."""
    try:
        jaxpr = _op_jaxpr(program, op)
    except Exception:  # non-traceable (callbacks etc.) — leave as-is
        return True
    eqns = jaxpr.jaxpr.eqns
    return len(eqns) <= 1 and (not eqns or eqns[0].primitive.name not in _INLINE)


def _op_jaxpr(program, op):
    in_avals = []
    for kind, val in op.arg_spec:
        if kind == "var":
            v = program._var_by_vid[val]
            in_avals.append(jax.ShapeDtypeStruct(v._value.shape, v._value.dtype))
    return jax.make_jaxpr(op.fn)(*in_avals)


def _prim_fn(primitive, params):
    if primitive.multiple_results:
        return lambda *a: tuple(primitive.bind(*a, **params))
    return lambda *a: primitive.bind(*a, **params)


def _emit(program, type_, fn, in_entries, out_vars=None):
    """Append an Operator with explicit inputs; returns output Variables.

    in_entries: list of ('var', Variable) | ('const', value).
    out_vars: existing Variables to write (splice back into old vids)."""
    arg_spec = []
    in_avals = []
    var_slots = []
    for i, (kind, val) in enumerate(in_entries):
        if kind == "var":
            arg_spec.append(("var", val._vid))
            in_avals.append(jax.ShapeDtypeStruct(val._value.shape, val._value.dtype))
            var_slots.append(i)
        else:
            arg_spec.append(("const", val))
    slot_set = set(var_slots)
    n_args = len(in_entries)

    def g(*var_vals):
        it = iter(var_vals)
        full = [next(it) if i in slot_set else arg_spec[i][1] for i in range(n_args)]
        with suspend_capture():
            return fn(*full)

    out_shape = jax.eval_shape(g, *in_avals)
    flat, tree = jax.tree_util.tree_flatten(out_shape)
    if out_vars is None:
        outs = [program.new_var(jax.ShapeDtypeStruct(o.shape, o.dtype)) for o in flat]
    else:
        outs = out_vars
    op = Operator(type_, g, arg_spec, {}, [o._vid for o in outs], tree)
    return op, outs


def _flatten_jaxpr(program, closed_jaxpr, in_entries, final_out_vars, new_ops):
    """Record one Operator per first-order eqn; inline higher-order eqns.

    in_entries: program-level ('var', Variable)/('const', value) per invar.
    final_out_vars: existing Variables for the jaxpr's outvars (or None)."""
    jaxpr = closed_jaxpr.jaxpr
    env = {}
    for var, entry in zip(jaxpr.invars, in_entries):
        env[var] = entry
    for var, const in zip(jaxpr.constvars, closed_jaxpr.consts):
        env[var] = ("const", const)

    def read(v):
        if isinstance(v, jax.extend.core.Literal):
            return ("const", v.val)
        return env[v]

    outvar_set = {id(v): i for i, v in enumerate(jaxpr.outvars) if not isinstance(v, jax.extend.core.Literal)}

    for eqn in jaxpr.eqns:
        ins = [read(v) for v in eqn.invars]
        name = eqn.primitive.name
        if name in _INLINE:
            inner = eqn.params[_INLINE[name]]
            if hasattr(inner, "jaxpr"):
                inner_closed = inner
            else:  # plain Jaxpr
                inner_closed = jax.extend.core.ClosedJaxpr(inner, ())
            results = _flatten_jaxpr(program, inner_closed, ins, None, new_ops)
            for v, r in zip(eqn.outvars, results):
                env[v] = r
            continue
        # final outputs that map 1:1 to an existing Variable reuse it
        outs_spec = None
        if final_out_vars is not None and len(eqn.outvars) == 1:
            ov = eqn.outvars[0]
            if id(ov) in outvar_set and _last_def(jaxpr, ov) is eqn:
                outs_spec = [final_out_vars[outvar_set[id(ov)]]]
        op, outs = _emit(program, name, _prim_fn(eqn.primitive, dict(eqn.params)), ins, outs_spec)
        new_ops.append(op)
        if eqn.primitive.multiple_results:
            for v, o in zip(eqn.outvars, outs):
                env[v] = ("var", o)
        else:
            env[eqn.outvars[0]] = ("var", outs[0])

    return [read(v) for v in jaxpr.outvars]


def _last_def(jaxpr, var):
    last = None
    for eqn in jaxpr.eqns:
        if any(v is var for v in eqn.outvars):
            last = eqn
    return last


def decompose_op(program, op, new_ops, closed=None):
    """Decompose one Operator; appends primitive Operators to new_ops."""
    if closed is None:
        closed = _op_jaxpr(program, op)
    in_entries = []
    for kind, val in op.arg_spec:
        if kind == "var":
            in_entries.append(("var", program._var_by_vid[val]))
    out_vars = [program._var_by_vid[vid] for vid in op.out_vids]
    results = _flatten_jaxpr(program, closed, in_entries, out_vars, new_ops)
    # any outvar not spliced in place gets an identity copy into the old var
    for entry, var in zip(results, out_vars):
        if entry[0] == "var" and entry[1] is var:
            continue
        if entry[0] == "const":
            cop, _ = _emit(program, "broadcast_in_dim",
                           lambda c=entry[1]: jnp.asarray(c), [], [var])
        else:
            cop, _ = _emit(program, "copy", lambda x: x, [("var", entry[1])], [var])
        new_ops.append(cop)


def decompose(program: Program, src_vars=None, blacklist=None, whitelist=None):
    """Rewrite composite ops into jax-primitive ops, in place (reference
    decomp.py:192).  whitelist: only these op types; blacklist: never these.
    Returns the program's dst vars for parity with the reference signature
    (src_vars pass through — vids are preserved)."""
    blacklist = set(blacklist or ())
    whitelist = set(whitelist) if whitelist else None
    block = program.global_block()
    new_list = []
    for op in block.ops:
        eligible = op.type not in blacklist and (whitelist is None or op.type in whitelist)
        if not eligible:
            new_list.append(op)
            continue
        try:
            closed = _op_jaxpr(program, op)  # traced once, reused below
        except Exception:
            new_list.append(op)  # untraceable op stays composite
            continue
        eqns = closed.jaxpr.eqns
        if len(eqns) <= 1 and (not eqns or eqns[0].primitive.name not in _INLINE):
            new_list.append(op)  # already primitive — keep op + its kwargs
            continue
        try:
            ops_out = []
            decompose_op(program, op, ops_out, closed)
        except Exception:
            new_list.append(op)
            continue
        new_list.extend(ops_out)
    block.ops = new_list
    program.version += 1
    return src_vars if src_vars is not None else program
