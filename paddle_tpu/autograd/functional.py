"""Functional differentiation (reference: python/paddle/autograd/functional
jacobian/hessian) — delegated to jax transforms, which also provide the
higher-order derivatives the tape doesn't."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor

__all__ = ["jacobian", "hessian", "vjp", "jvp"]


def _fn_on_values(func):
    def wrapped(*vals):
        args = [Tensor(v) for v in vals]
        out = func(*args)
        return out._value if isinstance(out, Tensor) else jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out
        )

    return wrapped


def jacobian(func, xs, create_graph=False, allow_unused=False):
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    vals = [x._value for x in xs_list]
    jac = jax.jacobian(_fn_on_values(func), argnums=tuple(range(len(vals))))(*vals)
    out = jax.tree_util.tree_map(Tensor, jac)
    return out[0] if single and isinstance(out, tuple) else out


def hessian(func, xs, create_graph=False, allow_unused=False):
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    vals = [x._value for x in xs_list]
    h = jax.hessian(_fn_on_values(func), argnums=tuple(range(len(vals))))(*vals)
    out = jax.tree_util.tree_map(Tensor, h)
    return out[0] if single and isinstance(out, tuple) else out


def vjp(func, xs, v=None):
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    vals = [x._value for x in xs_list]
    out, vjp_fn = jax.vjp(_fn_on_values(func), *vals)
    if v is None:
        cots = jnp.ones_like(out)
    else:
        cots = v._value if isinstance(v, Tensor) else jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, v
        )
    grads = vjp_fn(cots)
    grads_t = [Tensor(g) for g in grads]
    return Tensor(out) if not isinstance(out, tuple) else tuple(Tensor(o) for o in out), (
        grads_t[0] if single else grads_t
    )


def jvp(func, xs, v=None):
    single = not isinstance(xs, (list, tuple))
    xs_list = [xs] if single else list(xs)
    vals = [x._value for x in xs_list]
    if v is None:
        tangents = [jnp.ones_like(val) for val in vals]
    else:
        v_list = [v] if single else list(v)
        tangents = [t._value if isinstance(t, Tensor) else jnp.asarray(t) for t in v_list]
    out, tangent_out = jax.jvp(_fn_on_values(func), tuple(vals), tuple(tangents))
    wrap = lambda o: Tensor(o) if not isinstance(o, tuple) else tuple(Tensor(x) for x in o)  # noqa: E731
    return wrap(out), wrap(tangent_out)
