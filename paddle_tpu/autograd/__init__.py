"""Public autograd API (reference: python/paddle/autograd/__init__.py)."""

from paddle_tpu._core.autograd import (  # noqa: F401
    backward_multi,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from . import functional  # noqa: F401
from .functional import hessian, jacobian  # noqa: F401

_saved_tensor_hooks_stack = []


def _current_saved_tensor_hooks():
    return _saved_tensor_hooks_stack[-1] if _saved_tensor_hooks_stack else None


class saved_tensors_hooks:
    """Pack/unpack hooks for tensors saved by PyLayer.save_for_backward
    (reference: python/paddle/autograd/saved_tensors_hooks.py).

    The hook pair active at save time is captured with the saved tensors and
    applied on retrieval — the reference's offload-to-host use case.  Inside
    a compiled TrainStep, activation residency is XLA's job; use
    paddle.distributed.fleet.recompute / jax.checkpoint there instead.
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook, self.unpack_hook = pack_hook, unpack_hook

    def __enter__(self):
        _saved_tensor_hooks_stack.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _saved_tensor_hooks_stack.pop()


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference backward_mode.py:23)."""
    import jax.numpy as jnp

    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grads = [jnp.ones_like(t._value) for t in tensors]
    else:
        grad_tensors = grad_tensors if isinstance(grad_tensors, (list, tuple)) else [grad_tensors]
        grads = [
            jnp.ones_like(t._value) if g is None else g._value
            for t, g in zip(tensors, grad_tensors)
        ]
    backward_multi(tensors, grads, retain_graph)
