"""Public autograd API (reference: python/paddle/autograd/__init__.py)."""

from paddle_tpu._core.autograd import (  # noqa: F401
    backward_multi,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from . import functional  # noqa: F401


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward (reference backward_mode.py:23)."""
    import jax.numpy as jnp

    tensors = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    if grad_tensors is None:
        grads = [jnp.ones_like(t._value) for t in tensors]
    else:
        grad_tensors = grad_tensors if isinstance(grad_tensors, (list, tuple)) else [grad_tensors]
        grads = [
            jnp.ones_like(t._value) if g is None else g._value
            for t, g in zip(tensors, grad_tensors)
        ]
    backward_multi(tensors, grads, retain_graph)
