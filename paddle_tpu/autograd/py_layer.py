"""PyLayer — user-defined autograd ops (reference: python/paddle/autograd/py_layer.py).

The custom backward is attached to the tape as a hand-built GradNode, exactly
how the reference installs a PyLayer GradNode into the eager graph."""

from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp

from paddle_tpu._core import autograd as core_ag
from paddle_tpu._core.tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext"]


class _SavedTuple(tuple):
    """tuple that no-ops when called: supports both `ctx.saved_tensor`
    (this package's historical property form) and the reference's
    `ctx.saved_tensor()` method form."""

    def __call__(self):
        return self


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        from . import _current_saved_tensor_hooks

        hooks = _current_saved_tensor_hooks()
        if hooks is not None:
            self._saved = tuple(hooks[0](t) for t in tensors)
            self._saved_unpack = hooks[1]  # pair captured at save time
        else:
            self._saved = tuple(tensors)
            self._saved_unpack = None

    def _unpacked(self):
        unpack = getattr(self, "_saved_unpack", None)
        if unpack is not None:
            return _SavedTuple(unpack(t) for t in self._saved)
        return _SavedTuple(self._saved)

    @property
    def saved_tensor(self):
        # reference API is `ctx.saved_tensor()` (a method); _SavedTuple is
        # self-calling so both the property read and the call form work
        return self._unpacked()

    @property
    def saved_tensors(self):
        return self._unpacked()

    def mark_not_inplace(self, *args):
        self.not_inplace_tensors = args

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        # forward runs without taping its internals — PyLayer owns backward
        with core_ag.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        if not core_ag.is_grad_enabled():
            return outputs

        diff_inputs = [
            a
            for a in args
            if isinstance(a, Tensor)
            and not a.stop_gradient
            and jnp.issubdtype(a._value.dtype, jnp.inexact)
        ]
        if not diff_inputs:
            return outputs

        single = not isinstance(outputs, (list, tuple))
        out_list = [outputs] if single else list(outputs)
        out_tensors = [o for o in out_list if isinstance(o, Tensor)]
        out_avals = [(o._value.shape, o._value.dtype) for o in out_tensors]
        flat_tree = jax.tree_util.tree_structure(tuple(range(len(out_tensors))))

        backward_fn = cls.backward
        n_inputs = len(diff_inputs)
        input_positions = [i for i, a in enumerate(args) if any(a is d for d in diff_inputs)]
        n_args_tensors = len([a for a in args if isinstance(a, Tensor)])

        def vjp_fn(cot_struct):
            cots = jax.tree_util.tree_leaves(cot_struct)
            grad_out_tensors = [Tensor(c) for c in cots]
            with core_ag.no_grad():
                grads = backward_fn(ctx, *grad_out_tensors)
            grads = grads if isinstance(grads, (list, tuple)) else (grads,)
            # Map returned grads to diff_inputs: backward returns one grad per
            # *tensor* input of forward (reference contract).
            vals = []
            gi = 0
            tensor_args = [a for a in args if isinstance(a, Tensor)]
            grads_full = list(grads) + [None] * (len(tensor_args) - len(grads))
            per_tensor = dict(zip([id(t) for t in tensor_args], grads_full))
            for d in diff_inputs:
                g = per_tensor.get(id(d))
                vals.append(None if g is None else (g._value if isinstance(g, Tensor) else jnp.asarray(g)))
            return tuple(vals)

        def taped_vjp(cot_tensors):
            """create_graph path: the user backward runs WITH grad recording
            so its ops build the second-order graph (reference: double
            backward through PyLayer differentiates the custom backward,
            never the forward — straight-through estimators depend on it)."""
            grads = backward_fn(ctx, *cot_tensors)
            grads = grads if isinstance(grads, (list, tuple)) else (grads,)
            tensor_args = [a for a in args if isinstance(a, Tensor)]
            grads_full = list(grads) + [None] * (len(tensor_args) - len(grads))
            per_tensor = dict(zip([id(t) for t in tensor_args], grads_full))
            out = []
            for d in diff_inputs:
                g = per_tensor.get(id(d))
                if g is None:
                    out.append(None)
                else:
                    out.append(g if isinstance(g, Tensor) else Tensor(jnp.asarray(g)))
            return tuple(out)

        node = core_ag.GradNode(f"PyLayer[{cls.__name__}]", vjp_fn, diff_inputs, out_avals, flat_tree)
        node.taped_vjp = taped_vjp
        for i, o in enumerate(out_tensors):
            if jnp.issubdtype(o._value.dtype, jnp.inexact):
                o.stop_gradient = False
                o._grad_node = node
                o._out_index = i
            node.out_refs.append(weakref.ref(o))
        return outputs


# Alias used by some reference code paths
LegacyPyLayer = PyLayer
