"""paddle.profiler parity.

Reference: python/paddle/profiler/profiler.py:346 (Profiler with
HostTracer + CudaTracer/CUPTI, chrome-trace export, statistics tables,
schedules) over paddle/fluid/platform/profiler/.

TPU-native composition:
- **Host tracer**: RecordEvent instrumentation (used by the op funnel when a
  profiler is active) collecting ns-resolution host spans.
- **Device tracer**: jax.profiler start/stop_trace — XLA's XPlane/TensorBoard
  trace IS the CUPTI analog (per-kernel device timeline compiled in by XLA).
- Export: chrome trace JSON from host spans (device timeline lives in the
  XPlane dump directory), `summary()` statistics table aggregated by event.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass
from enum import Enum

import jax

__all__ = [
    "Profiler",
    "RecordEvent",
    "ProfilerTarget",
    "ProfilerState",
    "make_scheduler",
    "export_chrome_tracing",
    "load_profiler_result",
]

_active_profiler = None  # checked by the op funnel (cheap global)
_last_profiler = None  # most recent stopped Profiler (export_protobuf)


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


@dataclass
class _Span:
    name: str
    start_ns: int
    end_ns: int
    tid: int
    category: str = "host"


class _HostEventBuffer:
    def __init__(self):
        self.spans = []
        self._lock = threading.Lock()

    def add(self, span):
        with self._lock:
            self.spans.append(span)


class RecordEvent:
    """Host span (reference platform/profiler RecordEvent).  Also annotates
    the XLA device trace via jax.profiler.TraceAnnotation so host spans line
    up with device kernels in TensorBoard."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        prof = _active_profiler
        self._t0 = time.perf_counter_ns()
        if prof is not None:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        return self

    def end(self):
        prof = _active_profiler
        if prof is not None and self._t0 is not None:
            prof._buffer.add(
                _Span(self.name, self._t0, time.perf_counter_ns(), threading.get_ident())
            )
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None

    __enter__ = begin

    def __exit__(self, *exc):
        self.end()


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0, skip_first: int = 0):
    """Reference profiler.make_scheduler: step -> ProfilerState."""

    period = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


class Profiler:
    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None, timer_only=False, record_shapes=False, profile_memory=False, with_flops=False):
        self.targets = targets or [ProfilerTarget.CPU, ProfilerTarget.TPU]
        self.scheduler = scheduler if callable(scheduler) else None
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self.scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo, repeat=1)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self._buffer = _HostEventBuffer()
        self._step = 0
        self._recording = False
        self._xplane_dir = None
        self._step_spans = []
        self._step_t0 = None

    # ---------------------------------------------------------------- state
    def start(self):
        global _active_profiler
        if self.scheduler is not None:
            state = self.scheduler(0)
            _active_profiler = (
                self if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN) else None
            )
        else:
            _active_profiler = self
        self._recording = True
        if not self.timer_only and ProfilerTarget.TPU in self.targets:
            self._xplane_dir = os.path.abspath("profiler_log/xplane")
            os.makedirs(self._xplane_dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._xplane_dir)
            except Exception:
                self._xplane_dir = None
        self._step_t0 = time.perf_counter_ns()
        return self

    def stop(self):
        global _active_profiler
        if self._xplane_dir is not None:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._xplane_dir = None
        self._recording = False
        _active_profiler = None
        global _last_profiler
        _last_profiler = self
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter_ns()
        if self._step_t0 is not None:
            self._step_spans.append((self._step, now - self._step_t0))
        self._step_t0 = now
        self._step += 1
        if self.scheduler is not None:
            state = self.scheduler(self._step)
            global _active_profiler
            if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
                _active_profiler = self
            else:
                _active_profiler = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # --------------------------------------------------------------- export
    def export_chrome_tracing(self, path, *args):
        export_chrome_tracing(self, path)

    def export(self, path, format="json"):
        export_chrome_tracing(self, path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        """Aggregated statistics tables (reference profiler_statistic.py):
        Overview + per-category (Operator/Dataloader/UserDefined/...) tables
        with Calls/Total/Avg/Max/Min/Ratio columns, sortable via SortedKeys.
        Ends with the eager dispatch-cache counters when the fast path has
        seen traffic."""
        from .statistics import (checkpoint_line, cluster_line,
                                 compile_cache_line, decode_line,
                                 dispatch_cache_line, lora_line, mesh_line,
                                 pipeline_line, protocol_line, schedule_line,
                                 snapshot_line, summary_text, verify_line)

        out = summary_text(self._buffer.spans, self._step_spans,
                           sorted_by=sorted_by, op_detail=op_detail,
                           time_unit=time_unit, views=views)
        cache_line = dispatch_cache_line(dispatch_cache_stats())
        if cache_line:
            out = out + "\n" + cache_line
        comp_line = compile_cache_line(compile_stats())
        if comp_line:
            out = out + "\n" + comp_line
        dec_line = decode_line(decode_stats())
        if dec_line:
            out = out + "\n" + dec_line
        lr_line = lora_line(lora_stats())
        if lr_line:
            out = out + "\n" + lr_line
        ver_line = verify_line(verify_stats())
        if ver_line:
            out = out + "\n" + ver_line
        ml_line = mesh_line(mesh_lint_stats())
        if ml_line:
            out = out + "\n" + ml_line
        pr_line = protocol_line(protocol_lint_stats())
        if pr_line:
            out = out + "\n" + pr_line
        sched_line = schedule_line(schedule_search_stats())
        if sched_line:
            out = out + "\n" + sched_line
        ckpt_line = checkpoint_line(checkpoint_stats())
        if ckpt_line:
            out = out + "\n" + ckpt_line
        snap_line = snapshot_line(snapshot_stats())
        if snap_line:
            out = out + "\n" + snap_line
        cl_line = cluster_line(cluster_stats())
        if cl_line:
            out = out + "\n" + cl_line
        pp_line = pipeline_line(pipeline_stats())
        if pp_line:
            out = out + "\n" + pp_line
        print(out)
        return out


def export_chrome_tracing(profiler: Profiler, path: str):
    events = []
    for s in profiler._buffer.spans:
        events.append(
            {
                "name": s.name,
                "cat": s.category,
                "ph": "X",
                "ts": s.start_ns / 1e3,
                "dur": (s.end_ns - s.start_ns) / 1e3,
                "pid": 0,
                "tid": s.tid % 10_000,
            }
        )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return path


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


class SortedKeys:
    """Summary-table sort keys (reference:
    python/paddle/profiler/profiler_statistic.py SortedKeys)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """Summary view selector (reference: profiler.py SummaryView)."""

    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(path=None):
    """reference: profiler export to protobuf dump.  The host-span tree
    exports via the chrome-trace JSON (load_profiler_result-compatible);
    protobuf adds no information on this runtime, so this writes the same
    payload with the requested extension."""
    prof = _active_profiler or _last_profiler
    if prof is None:
        raise RuntimeError("export_protobuf: no active/finished Profiler")
    prof.export(path or "profiler.pb")


__all__ += ["SortedKeys", "SummaryView", "export_protobuf"]


def dispatch_cache_stats(reset: bool = False) -> dict:
    """Counters of the eager dispatch fast path (FLAGS_eager_op_jit):
    hits / misses / traces / evictions / bypasses plus size, capacity and
    whether the path is enabled.  `reset=True` zeroes the counters (cached
    entries stay).  A healthy steady-state training loop shows hits
    dominating with traces flat; climbing traces mean shape/dtype churn is
    defeating the cache."""
    from paddle_tpu._core import dispatch

    stats = dispatch.cache.stats()
    if reset:
        dispatch.cache.reset_stats()
    return stats


def reset_dispatch_cache():
    """Drop every cached dispatch entry and zero the counters."""
    from paddle_tpu._core import dispatch

    dispatch.cache.clear()
    dispatch.cache.reset_stats()


def decode_stats(reset: bool = False) -> dict:
    """Serving decode counters (paddle_tpu.serving): compiled-program
    dispatches, emitted tokens, host sync seconds (time blocked
    materializing device results), total step seconds and derived
    tokens_per_sec.  Macro-step decoding (FLAGS_decode_chunk > 1) shows
    tokens >> dispatches; tokens ~= dispatches means every token pays a
    host round-trip (the per-token path).  Also the prefix-cache tier
    (FLAGS_prefix_cache): prefix_hits/_misses per admission,
    prefix_hit_tokens (prompt tokens whose prefill was avoided by page
    reuse), prefix_evictions (LRU reclaims under pool pressure); and the
    capacity tier: pool_bytes of the most recent engine, resident_peak
    concurrently-active requests, and derived pool_bytes_per_resident —
    the number int8 KV pools (FLAGS_kv_cache_dtype) roughly halve.
    The overload-discipline tier (docs/DECODE.md admission scheduler):
    prefill_chunks (interleaved block-sized prefill chunks run between
    decode dispatches), preemptions / preempt_readmits (LOW-priority
    parking traffic), parked_requests (a GAUGE of the live parking lot,
    preserved across resets like the LoRA slot gauges), and the
    per-SLO-class admitted_/completed_{high,normal,low} breakdown.
    Zeros when no engine ran.  Serving owns the counters — one schema,
    no drift."""
    from paddle_tpu import serving

    return serving.decode_stats(reset=reset)


def lora_stats(reset: bool = False) -> dict:
    """Multi-tenant LoRA serving counters (paddle_tpu.serving + nn/lora.py,
    docs/LORA.md): adapter slots resident/total on the most recent pack
    engine, hot swaps (adapter installs into a slot) and evictions, decode
    dispatches that gathered per-row adapter A/B from the pack, and
    prefix-cache slot-epoch bumps (each invalidates exactly one slot's
    cached subtree).  Zeros when no adapter engine ran.  The serving
    module owns the counters — one schema, no drift."""
    from paddle_tpu import serving

    return serving.lora_stats(reset=reset)


def compile_stats(reset: bool = False) -> dict:
    """Trace-time / XLA-compile-time / persistent-cache counters for this
    process (fed by jax.monitoring; see _core.compile_cache): traces,
    trace_seconds, compiles, compile_seconds, persistent_cache_hits /
    _misses, compile_seconds_saved, cache_dir.  A warm start (TrainStep
    .warmup + FLAGS_compilation_cache_dir) shows hits with near-zero
    compile_seconds; climbing compiles in steady state mean signature
    churn is defeating jax's executable cache."""
    from paddle_tpu._core import compile_cache

    stats = compile_cache.compile_stats()
    if reset:
        compile_cache.reset_compile_stats()
    return stats


def verify_stats(reset: bool = False) -> dict:
    """Static-IR verify-mode counters (FLAGS_verify_programs; see
    static/verify.py and docs/VERIFIER.md): programs verified/failed,
    violations found, abstract-eval skips, differential checks run/failed,
    and pattern rewrites the use-def guard refused.  A healthy verified run
    shows failures and violations at zero; non-zero rewrites_refused means
    a fusion pattern tried to consume a value the program still needs."""
    from paddle_tpu.static import verify as _verify

    return _verify.verify_stats(reset=reset)


def mesh_lint_stats(reset: bool = False) -> dict:
    """Mesh-lint counters (FLAGS_verify_sharding; see static/mesh_lint.py
    and docs/MESH_LINT.md): entries linted (programs + train steps +
    serving engines) and failed, violations found, collectives and
    sharding constraints congruence-checked, tensor placements validated,
    donation-contract checks, per-device memory estimates computed, and
    op fns the abstract tracer had to skip.  A healthy verified run shows
    failed and violations at zero; nonzero means a placement/collective/
    donation hazard reached a build path — the raised MeshLintError names
    the site.  The mesh_lint module owns the counters — one schema, no
    drift."""
    from paddle_tpu.static import mesh_lint as _ml

    return _ml.mesh_lint_stats(reset=reset)


def protocol_lint_stats(reset: bool = False) -> dict:
    """Protocol-lint counters (see static/protocol_lint.py and
    docs/PROTOCOL_LINT.md): model-check scenarios run, abstract-cluster
    states and transitions explored, per-state invariant evaluations,
    violations and deadlocks found, plus the blocking-call AST pass
    (files linted, functions scanned, blocking call sites classified).
    A healthy run shows violations and deadlocks at zero — nonzero means
    an interleaving of the abstract router/replica/prefill/standby model
    broke a named invariant of serving/protocol.py (the raised
    ProtocolLintError carries the minimal counterexample trace) or a
    wait escaped retry_backoff's shared-deadline discipline.  The
    protocol_lint module owns the counters — one schema, no drift."""
    from paddle_tpu.static import protocol_lint as _pl

    return _pl.protocol_lint_stats(reset=reset)


def schedule_search_stats(reset: bool = False) -> dict:
    """Pallas schedule-search counters (FLAGS_schedule_search; see
    static/schedule_search.py and docs/SCHEDULE_SEARCH.md): subgraphs
    discovered and searched, candidate tilings enumerated, candidates
    pruned by the roofline model vs the VMEM budget vs the numerics
    parity gate, candidates measured on device, subgraphs accepted
    (schedule beat XLA by the win margin) vs disabled, and cache service
    (accepted configs / disabled skips reloaded from the per-device
    autotune cache).  Steady state shows cache hits with measured flat —
    climbing measured means shape churn is defeating the schedule cache.
    The schedule_search module owns those counters — one schema, no
    drift; the phase-2 decode-chain counters
    (decode_chains_found/accepted/disabled/mesh_skipped) are owned by the
    SERVING module (discovery happens at the engine) and merged in
    here."""
    from paddle_tpu import serving as _serving
    from paddle_tpu.static import schedule_search as _ss

    out = _ss.schedule_search_stats(reset=reset)
    out.update(_serving.schedule_decode_stats(reset=reset))
    return out


def snapshot_stats(reset: bool = False) -> dict:
    """Live-engine snapshot counters (serving/snapshot.py,
    docs/CHECKPOINT.md serving section): engine snapshots saved and
    restored, bytes committed through the atomic protocol, seconds spent
    capturing+committing, torn snapshots skipped while resolving the
    newest restorable state, and drain() migrations.  Healthy:
    corrupt_skipped at zero (nonzero means a kill landed mid-commit and
    auto-restore passed over the torn dir — by design, but worth
    knowing).  The serving module owns the counters — one schema, no
    drift."""
    from paddle_tpu import serving

    return serving.snapshot_stats(reset=reset)


def cluster_stats(reset: bool = False) -> dict:
    """Disaggregated serving-cluster counters (serving/cluster.py,
    docs/SERVING_CLUSTER.md): live decode replicas (a gauge), heartbeat
    periods missed across the fleet, requests re-dispatched after a
    replica death or drain, KV pages (and wire bytes) shipped
    prefill->decode, retries on the shipping path, and queued requests
    migrated by graceful drains.  Healthy steady state shows
    heartbeats_missed and redispatches flat; climbing redispatches means
    replicas are dying faster than they respawn.  The warm-start tier
    adds standbys_warm (gauge of ready standbys), promotions (standbys
    that took a dead replica's slot), warmups/warmup_seconds (worker AOT
    warm reports), and respawn_compile_hits/misses (the persistent
    compile-cache counters respawned workers reported at boot —
    hits > 0 is the warmed-respawn contract).  The cluster module owns
    the counters — one schema, no drift."""
    from paddle_tpu.serving import cluster as _cluster

    return _cluster.cluster_stats(reset=reset)


def pipeline_stats(reset: bool = False) -> dict:
    """Pipeline-schedule counters (fleet/meta_parallel/schedules.py,
    docs/PIPELINE.md): pipeline step programs built, scan ticks traced
    (forward + split-backward), F/B/W stage-microbatch slots, stage-ticks
    spent on warmup/drain bubble work, and collective-permute hops issued
    by comm/compute-overlap chains (ShardedTrainStep comm_overlap /
    overlap_grad_sync).  Counted when a program is built or dispatched
    from python — once per trace under a compiled TrainStep, per call in
    eager (the mesh-lint counter convention).  w_slots nonzero means a
    zero-bubble split-backward schedule (ZB-H1) is live.  The schedules
    module owns the counters — one schema, no drift."""
    from paddle_tpu.distributed.fleet.meta_parallel import schedules as _sched

    return _sched.pipeline_stats(reset=reset)


def checkpoint_stats(reset: bool = False) -> dict:
    """CheckpointManager counters (distributed/checkpoint/manager.py):
    saves issued (async_saves of them backgrounded), atomic commits,
    bytes written, seconds split into snapshot (synchronous device→host)
    vs write (background disk IO) vs backpressure (save() blocked on an
    in-flight write), GC deletions, restores, and checkpoints skipped as
    corrupt/torn during auto-resume.  Healthy: corrupt_skipped and errors
    at zero, backpressure near zero (writes finish inside the save
    interval).  The checkpoint module owns the counters — one schema, no
    drift."""
    from paddle_tpu.distributed.checkpoint import manager as _ckpt_manager

    return _ckpt_manager.checkpoint_stats(reset=reset)


__all__ += ["dispatch_cache_stats", "reset_dispatch_cache", "compile_stats",
            "decode_stats", "lora_stats", "verify_stats", "mesh_lint_stats",
            "schedule_search_stats", "checkpoint_stats", "snapshot_stats",
            "cluster_stats", "pipeline_stats", "protocol_lint_stats"]


def _compile_and_analyze(fn, example_args):
    """jit-compile fn on the current backend and normalize its cost
    analysis (list vs dict across jax versions)."""
    import jax

    from paddle_tpu._core.tensor import Tensor

    vals = [a._value if isinstance(a, Tensor) else a for a in example_args]
    compiled = jax.jit(fn).lower(*vals).compile()
    analyses = compiled.cost_analysis()
    if isinstance(analyses, (list, tuple)):
        analyses = analyses[0] if analyses else {}
    return compiled, vals, dict(analyses or {})


def cost_analysis(fn, *example_args):
    """Compile `fn` for the current backend and return XLA's cost analysis
    (flops, bytes accessed, ...) — the per-op cost table the reference
    builds by profiling (python/paddle/cost_model/static_op_benchmark.json),
    here read straight from the compiler."""
    return _compile_and_analyze(fn, example_args)[2]


def estimate_mfu(fn, *example_args, runtime_s=None, peak_tflops=None):
    """Model-FLOPs-utilization report for a compiled step.

    flops come from XLA's cost analysis of the compiled executable;
    runtime_s (measured seconds per call; measured here with one timed call
    after warmup when omitted); peak from the device kind
    (device/peaks.py).  Returns {"flops", "runtime_s", "achieved_tflops",
    "peak_tflops", "mfu"} — mfu is 0.0 on CPU (no meaningful peak)."""
    import time

    import jax

    from paddle_tpu.device.peaks import device_peak_tflops

    compiled, vals, analyses = _compile_and_analyze(fn, example_args)
    flops = float(analyses.get("flops", 0.0))
    if runtime_s is None:
        # RTT-cancelling adaptive timer (readback-synced, differences two
        # batch lengths so the tunnel round trip drops out — the same
        # methodology the kernel autotuner uses)
        from paddle_tpu.ops.autotune import _time_fn

        runtime_s = _time_fn(compiled, vals, iters=2) / 1e3
    d = jax.devices()[0]
    if peak_tflops is None:
        peak_tflops = device_peak_tflops(d.device_kind, d.platform)
    achieved = flops / runtime_s / 1e12 if runtime_s > 0 else 0.0
    mfu = achieved / peak_tflops if peak_tflops else 0.0
    return {
        "flops": flops,
        "runtime_s": runtime_s,
        "achieved_tflops": achieved,
        "peak_tflops": peak_tflops,
        "mfu": mfu,
    }


__all__ += ["cost_analysis", "estimate_mfu"]
