"""Per-op aggregated profiler statistics tables.

Reference: python/paddle/profiler/profiler_statistic.py — StatisticData
aggregates the event tree into the Overview / Operator / Kernel / UserDefined
summary tables printed by Profiler.summary(), sortable via SortedKeys, with
per-row Calls / Total / Avg / Max / Min and ratio columns.

TPU-native: host spans (RecordEvent) are the event source; the funnel tags
every op span "op::<type>", steps are tagged by the profiler itself, and
remaining spans are user-defined.  Device time on this runtime is the
compiled step's wall share (XLA owns kernel scheduling; per-kernel device
times live in the TensorBoard/XPlane trace the chrome export lines up with).
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["EventSummary", "StatisticData", "summary_text",
           "dispatch_cache_line", "compile_cache_line", "decode_line",
           "lora_line"]

_UNITS = {"s": 1e9, "ms": 1e6, "us": 1e3, "ns": 1.0}


@dataclass
class EventSummary:
    """Aggregated stats for one event name (reference EventSummary)."""

    name: str
    calls: int = 0
    total_ns: int = 0
    max_ns: int = 0
    min_ns: int = field(default=2 ** 63 - 1)

    def add(self, dur_ns):
        self.calls += 1
        self.total_ns += dur_ns
        self.max_ns = max(self.max_ns, dur_ns)
        self.min_ns = min(self.min_ns, dur_ns)

    @property
    def avg_ns(self):
        return self.total_ns / self.calls if self.calls else 0.0


def _category(name):
    if name.startswith("op::"):
        return "Operator"
    if name.startswith("step"):
        return "ProfileStep"
    if "dataloader" in name.lower() or name.startswith("io::"):
        return "Dataloader"
    if name.startswith("comm::") or name.startswith("nccl") or "all_reduce" in name:
        return "Communication"
    return "UserDefined"


class StatisticData:
    """Aggregates spans into per-category EventSummary maps
    (reference StatisticData over the node trees)."""

    def __init__(self, spans, step_spans=()):
        self.by_category: dict[str, dict[str, EventSummary]] = {}
        self.wall_ns = 0
        t0, t1 = None, None
        for s in spans:
            cat = _category(s.name)
            bucket = self.by_category.setdefault(cat, {})
            ev = bucket.get(s.name)
            if ev is None:
                ev = bucket[s.name] = EventSummary(s.name)
            ev.add(s.end_ns - s.start_ns)
            t0 = s.start_ns if t0 is None else min(t0, s.start_ns)
            t1 = s.end_ns if t1 is None else max(t1, s.end_ns)
        self.step_spans = list(step_spans)
        if self.step_spans:
            self.wall_ns = sum(d for _, d in self.step_spans)
        elif t0 is not None:
            self.wall_ns = t1 - t0

    def sorted_events(self, category, sorted_by=None):
        from paddle_tpu.profiler import SortedKeys

        events = list(self.by_category.get(category, {}).values())
        key = {
            None: lambda e: -e.total_ns,
            SortedKeys.CPUTotal: lambda e: -e.total_ns,
            SortedKeys.GPUTotal: lambda e: -e.total_ns,
            SortedKeys.CPUAvg: lambda e: -e.avg_ns,
            SortedKeys.GPUAvg: lambda e: -e.avg_ns,
            SortedKeys.CPUMax: lambda e: -e.max_ns,
            SortedKeys.GPUMax: lambda e: -e.max_ns,
            SortedKeys.CPUMin: lambda e: e.min_ns,
            SortedKeys.GPUMin: lambda e: e.min_ns,
        }.get(sorted_by, lambda e: -e.total_ns)
        return sorted(events, key=key)


def _fmt_time(ns, unit):
    return f"{ns / _UNITS[unit]:.3f}"


def _table(title, headers, rows, widths):
    total_w = sum(widths)
    out = [
        "-" * total_w,
        title.center(total_w),
        "-" * total_w,
        "".join(h.rjust(w) if i else h.ljust(w) for i, (h, w) in enumerate(zip(headers, widths))),
        "=" * total_w,
    ]
    for row in rows:
        out.append("".join(
            (c.rjust(w) if i else c.ljust(w))
            for i, (c, w) in enumerate(zip(row, widths))))
    out.append("-" * total_w)
    return out


def summary_text(spans, step_spans=(), sorted_by=None, op_detail=True,
                 time_unit="ms", views=None):
    """The reference Profiler.summary() table set: Overview + per-category
    tables with Calls / Total / Avg / Max / Min / Ratio(%)."""
    if time_unit not in _UNITS:
        raise ValueError(f"time_unit must be one of {sorted(_UNITS)}")
    data = StatisticData(spans, step_spans)
    wall = max(data.wall_ns, 1)
    u = time_unit
    lines = []

    # ---- Overview: wall breakdown per category (reference OverView)
    rows = []
    for cat, events in sorted(data.by_category.items()):
        tot = sum(e.total_ns for e in events.values())
        calls = sum(e.calls for e in events.values())
        rows.append([cat, str(calls), _fmt_time(tot, u),
                     f"{100.0 * tot / wall:.2f}"])
    if data.step_spans:
        rows.append(["ProfileStep(wall)", str(len(data.step_spans)),
                     _fmt_time(data.wall_ns, u), "100.00"])
    lines += _table(f"Overview Summary (time unit: {u})",
                    ["Category", "Calls", f"Total({u})", "Ratio(%)"],
                    rows, [34, 10, 16, 12])
    lines.append("")

    # ---- per-category detail tables
    wanted = set(views) if views else None
    for cat in sorted(data.by_category):
        if wanted is not None and cat not in wanted:
            continue
        if cat == "ProfileStep" and not op_detail:
            continue
        rows = []
        for e in data.sorted_events(cat, sorted_by):
            name = e.name[4:] if e.name.startswith("op::") else e.name
            rows.append([
                name[:38], str(e.calls), _fmt_time(e.total_ns, u),
                _fmt_time(e.avg_ns, u), _fmt_time(e.max_ns, u),
                _fmt_time(e.min_ns, u), f"{100.0 * e.total_ns / wall:.2f}",
            ])
        title = {"Operator": "Operator Summary", "UserDefined": "UserDefined Summary",
                 "Dataloader": "Dataloader Summary", "Communication": "Communication Summary",
                 "ProfileStep": "ProfileStep Summary"}.get(cat, f"{cat} Summary")
        lines += _table(f"{title} (time unit: {u})",
                        ["Name", "Calls", f"Total({u})", f"Avg({u})",
                         f"Max({u})", f"Min({u})", "Ratio(%)"],
                        rows, [39, 8, 13, 13, 13, 13, 10])
        lines.append("")

    if data.step_spans:
        n = len(data.step_spans)
        lines.append(
            f"steps: {n}  avg step: {data.wall_ns / n / _UNITS[u]:.3f} {u}")
    return "\n".join(lines)


def dispatch_cache_line(stats: dict) -> str:
    """One-line rendering of the eager dispatch-cache counters for
    Profiler.summary(); empty when the fast path has seen no traffic."""
    if not (stats.get("hits") or stats.get("misses") or stats.get("bypasses")):
        return ""
    total = stats["hits"] + stats["misses"]
    rate = 100.0 * stats["hits"] / total if total else 0.0
    return (
        "Eager dispatch cache [%s]: hits=%d misses=%d (%.1f%% hit) traces=%d "
        "evictions=%d bypasses=%d entries=%d/%d"
        % ("on" if stats.get("enabled") else "off", stats["hits"],
           stats["misses"], rate, stats["traces"], stats["evictions"],
           stats["bypasses"], stats["size"], stats["capacity"])
    )


def decode_line(stats: dict) -> str:
    """One-line rendering of the serving decode counters for
    Profiler.summary(); empty when no engine dispatched this process.
    With the prefix cache or capacity counters active, a second line
    reports hits/misses/avoided-prefill-tokens/evictions and pool bytes
    per resident request (the int8-KV capacity metric)."""
    if not stats.get("dispatches"):
        return ""
    toks = stats.get("tokens", 0)
    disp = stats["dispatches"]
    line = (
        "Serving decode: tokens=%d dispatches=%d (%.1f tok/dispatch, "
        "last chunk D=%d) tokens/s=%.1f sync=%.3fs of %.3fs"
        % (toks, disp, toks / disp if disp else 0.0,
           stats.get("last_chunk", 0), stats.get("tokens_per_sec", 0.0),
           stats.get("sync_seconds", 0.0), stats.get("step_seconds", 0.0))
    )
    lookups = stats.get("prefix_hits", 0) + stats.get("prefix_misses", 0)
    if lookups or stats.get("resident_peak"):
        line += (
            "\nPrefix cache: hits=%d misses=%d prefill_avoided_tokens=%d "
            "evictions=%d; pool bytes/resident=%.0f (peak %d resident)"
            % (stats.get("prefix_hits", 0), stats.get("prefix_misses", 0),
               stats.get("prefix_hit_tokens", 0),
               stats.get("prefix_evictions", 0),
               stats.get("pool_bytes_per_resident", 0.0),
               stats.get("resident_peak", 0))
        )
    if stats.get("mesh_shape"):
        # TP-sharded engine: per-device pool footprint vs the global total
        line += (
            "\nSharded serving: mesh=%s pool_bytes/device=%d (global %d)"
            % (stats["mesh_shape"], stats.get("pool_bytes_per_device", 0),
               stats.get("pool_bytes", 0))
        )
    classes = sum(stats.get("admitted_" + c, 0)
                  + stats.get("completed_" + c, 0)
                  for c in ("high", "normal", "low"))
    if (stats.get("prefill_chunks") or stats.get("preemptions")
            or stats.get("parked_requests") or classes):
        # overload-discipline tier: interleaved prefill chunks, the
        # preemption parking lot, and the per-SLO-class breakdown
        line += (
            "\nServing admission: prefill_chunks=%d preemptions=%d "
            "readmits=%d parked=%d; admitted h/n/l=%d/%d/%d "
            "completed h/n/l=%d/%d/%d"
            % (stats.get("prefill_chunks", 0), stats.get("preemptions", 0),
               stats.get("preempt_readmits", 0),
               stats.get("parked_requests", 0),
               stats.get("admitted_high", 0), stats.get("admitted_normal", 0),
               stats.get("admitted_low", 0), stats.get("completed_high", 0),
               stats.get("completed_normal", 0),
               stats.get("completed_low", 0))
        )
    return line


def lora_line(stats: dict) -> str:
    """One-line rendering of the multi-tenant LoRA serving counters for
    Profiler.summary(); empty when no adapter-pack engine ran this
    process (docs/LORA.md)."""
    if not (stats.get("swaps") or stats.get("gather_dispatches")
            or stats.get("slots_resident")):
        return ""
    return (
        "LoRA serving: slots=%d/%d resident, swaps=%d evictions=%d "
        "gather_dispatches=%d cache_epochs=%d"
        % (stats.get("slots_resident", 0), stats.get("slots_total", 0),
           stats.get("swaps", 0), stats.get("evictions", 0),
           stats.get("gather_dispatches", 0), stats.get("cache_epochs", 0))
    )


def verify_line(stats: dict) -> str:
    """One-line rendering of the IR verify-mode counters for
    Profiler.summary(); empty when FLAGS_verify_programs never ran.
    A nonzero rewrites_refused alone still renders the line: the rewrite
    driver rolls fusions back flag-independently, and a refusal is exactly
    the red flag verify_stats() tells users to watch for."""
    if not (stats.get("programs_verified") or stats.get("differential_checks")
            or stats.get("rewrites_refused")):
        return ""
    return (
        "IR verify: programs=%d failed=%d violations=%d abstract_skips=%d; "
        "differential checks=%d failed=%d; rewrites refused=%d"
        % (stats["programs_verified"], stats["programs_failed"],
           stats["violations"], stats["abstract_eval_skips"],
           stats["differential_checks"], stats["differential_failures"],
           stats["rewrites_refused"])
    )


def mesh_line(stats: dict) -> str:
    """One-line rendering of the mesh-lint counters for Profiler.summary();
    empty when FLAGS_verify_sharding never ran this process.  entries_failed
    or violations nonzero is the red flag: a placement/collective/donation
    hazard reached a build path (the error names the site)."""
    if not (stats.get("entries_linted") or stats.get("collectives_checked")
            or stats.get("placements_checked")):
        return ""
    return (
        "Mesh lint: entries=%d failed=%d violations=%d; collectives=%d "
        "constraints=%d placements=%d donation_checks=%d mem_estimates=%d "
        "trace_skips=%d"
        % (stats["entries_linted"], stats["entries_failed"],
           stats["violations"], stats["collectives_checked"],
           stats["constraints_checked"], stats["placements_checked"],
           stats["donation_checks"], stats["memory_estimates"],
           stats["trace_skips"])
    )


def protocol_line(stats: dict) -> str:
    """One-line rendering of the protocol-lint counters for
    Profiler.summary(); empty when neither the model checker nor the
    blocking-call pass ran this process.  violations or deadlocks nonzero
    is the red flag: an interleaving of the abstract cluster model broke
    a named invariant (the ProtocolLintError carries the minimal
    counterexample trace), or a blocking call site escaped the shared
    deadline discipline."""
    if not (stats.get("scenarios_checked") or stats.get("files_linted")):
        return ""
    return (
        "Protocol lint: scenarios=%d states=%d transitions=%d "
        "invariant_checks=%d violations=%d deadlocks=%d; files=%d "
        "functions=%d blocking_calls=%d"
        % (stats["scenarios_checked"], stats["model_states"],
           stats["model_transitions"], stats["invariant_checks"],
           stats["violations"], stats["deadlocks"], stats["files_linted"],
           stats["functions_scanned"], stats["blocking_calls_checked"])
    )


def schedule_line(stats: dict) -> str:
    """One-line rendering of the Pallas schedule-search counters for
    Profiler.summary(); empty when the search tier never ran this process.
    `disabled` nonzero is healthy honesty (the measured-win gate found XLA
    faster and said so); `measured` climbing in steady state means shape
    churn is defeating the per-device schedule cache.  A second line
    reports the serving decode-chain verdicts (phase 2) when any engine
    consulted the searcher — mesh_fused counts TP-sharded engines whose
    macro-step adopted the shard_map chain, mesh_skipped the sharded
    engines with replicated pools that kept the unfused scan body by
    design; a third line mirrors the chunked-prefill chain verdicts
    (PrefillChainSpec) when any engine searched one."""
    decode = any(stats.get(k) for k in (
        "decode_chains_found", "decode_chains_accepted",
        "decode_chains_disabled", "decode_chains_mesh_skipped",
        "decode_chains_mesh_fused"))
    prefill = any(stats.get(k) for k in (
        "prefill_chains_found", "prefill_chains_accepted",
        "prefill_chains_disabled"))
    if not (stats.get("subgraphs_found") or stats.get("cache_hits")
            or stats.get("disabled_hits") or decode or prefill):
        return ""
    line = (
        "Schedule search: subgraphs=%d candidates=%d pruned_roofline=%d "
        "pruned_vmem=%d pruned_parity=%d measured=%d accepted=%d "
        "disabled=%d; cache hits=%d disabled_hits=%d"
        % (stats["subgraphs_found"], stats["candidates"],
           stats["pruned_roofline"], stats["pruned_vmem"],
           stats.get("pruned_parity", 0),
           stats["measured"], stats["accepted"], stats["disabled"],
           stats["cache_hits"], stats["disabled_hits"])
    )
    if decode:
        line += (
            "\nDecode chains: found=%d accepted=%d disabled=%d "
            "mesh_fused=%d mesh_skipped=%d"
            % (stats.get("decode_chains_found", 0),
               stats.get("decode_chains_accepted", 0),
               stats.get("decode_chains_disabled", 0),
               stats.get("decode_chains_mesh_fused", 0),
               stats.get("decode_chains_mesh_skipped", 0))
        )
    if prefill:
        line += (
            "\nPrefill chains: found=%d accepted=%d disabled=%d"
            % (stats.get("prefill_chains_found", 0),
               stats.get("prefill_chains_accepted", 0),
               stats.get("prefill_chains_disabled", 0))
        )
    return line


def checkpoint_line(stats: dict) -> str:
    """One-line rendering of the CheckpointManager counters for
    Profiler.summary(); empty when no checkpoint activity this process.
    corrupt_skipped or errors nonzero is the red flag: auto-resume passed
    over a torn checkpoint, or a background write failed."""
    if not (stats.get("saves") or stats.get("restores")
            or stats.get("corrupt_skipped")):
        return ""
    return (
        "Checkpoint: saves=%d (async=%d) commits=%d bytes=%d "
        "snapshot=%.3fs write=%.3fs backpressure=%.3fs gc_deleted=%d; "
        "restores=%d corrupt_skipped=%d errors=%d"
        % (stats["saves"], stats["async_saves"], stats["commits"],
           stats["bytes_written"], stats["snapshot_seconds"],
           stats["write_seconds"], stats["backpressure_seconds"],
           stats["gc_deleted"], stats["restores"], stats["corrupt_skipped"],
           stats["errors"])
    )


def cluster_line(stats: dict) -> str:
    """One-line rendering of the disaggregated serving-cluster counters
    for Profiler.summary(); empty when no cluster ran this process
    (serving/cluster.py).  redispatches nonzero means a replica died or
    drained and its accepted requests moved — the fail-over machinery
    working, surfaced so an unstable fleet is visible at a glance.  The
    warm-start tier rides the same line: standbys_warm is the live gauge,
    promotions counts standbys that took a dead replica's slot, warmups/
    warmup_s the worker AOT warm reports, and respawn_cache h/m the
    persistent compile-cache hits/misses respawned workers booted with."""
    if not (stats.get("replicas_alive") or stats.get("redispatches")
            or stats.get("pages_shipped") or stats.get("drain_migrations")
            or stats.get("heartbeats_missed") or stats.get("standbys_warm")
            or stats.get("promotions") or stats.get("warmups")):
        return ""
    return (
        "Serving cluster: replicas_alive=%d heartbeats_missed=%d "
        "redispatches=%d pages_shipped=%d ship_bytes=%d ship_retries=%d "
        "drain_migrations=%d standbys_warm=%d promotions=%d warmups=%d "
        "warmup_s=%.2f respawn_cache=%dh/%dm"
        % (stats["replicas_alive"], stats["heartbeats_missed"],
           stats["redispatches"], stats["pages_shipped"],
           stats["ship_bytes"], stats["ship_retries"],
           stats["drain_migrations"], stats.get("standbys_warm", 0),
           stats.get("promotions", 0), stats.get("warmups", 0),
           stats.get("warmup_seconds", 0.0),
           stats.get("respawn_compile_hits", 0),
           stats.get("respawn_compile_misses", 0))
    )


def snapshot_line(stats: dict) -> str:
    """One-line rendering of the live-engine snapshot counters for
    Profiler.summary(); empty when no engine snapshot activity this
    process (serving/snapshot.py).  corrupt_skipped nonzero means a kill
    landed mid-commit and restore passed over the torn dir — the
    protocol working as designed, surfaced so nobody wonders where a
    snapshot went."""
    if not (stats.get("saves") or stats.get("restores")
            or stats.get("corrupt_skipped")):
        return ""
    return (
        "Engine snapshot: saves=%d restores=%d bytes=%d snapshot=%.3fs "
        "corrupt_skipped=%d drains=%d"
        % (stats["saves"], stats["restores"], stats["bytes"],
           stats["snapshot_seconds"], stats["corrupt_skipped"],
           stats["drains"])
    )


def pipeline_line(stats: dict) -> str:
    """One-line rendering of the pipeline-schedule counters for
    Profiler.summary(); empty when no pipeline program ran this process
    (fleet/meta_parallel/schedules.py, docs/PIPELINE.md).  w_slots nonzero
    means a zero-bubble split-backward schedule is live; overlap_issued
    counts the collective-permute hops of comm/compute-overlap grad-sync
    chains."""
    if not (stats.get("programs") or stats.get("overlap_issued")):
        return ""
    return (
        "Pipeline: programs=%d ticks=%d slots F=%d B=%d W=%d "
        "bubble_ticks=%d overlap_issued=%d"
        % (stats["programs"], stats["ticks"], stats["f_slots"],
           stats["b_slots"], stats["w_slots"], stats["bubble_ticks"],
           stats["overlap_issued"])
    )


def compile_cache_line(stats: dict) -> str:
    """One-line rendering of the trace/compile + persistent-cache counters
    for Profiler.summary(); empty when nothing compiled this process."""
    if not (stats.get("compiles") or stats.get("traces")):
        return ""
    line = (
        "XLA compile: traces=%d (%.2fs) compiles=%d (%.2fs)"
        % (stats["traces"], stats["trace_seconds"], stats["compiles"],
           stats["compile_seconds"])
    )
    if stats.get("cache_dir"):
        line += (
            "; persistent cache [%s]: hits=%d misses=%d saved=%.2fs"
            % (stats["cache_dir"], stats["persistent_cache_hits"],
               stats["persistent_cache_misses"],
               stats["compile_seconds_saved"])
        )
    return line
