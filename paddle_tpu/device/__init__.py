"""Device API (reference: python/paddle/device/__init__.py:265 set_device,
cuda stream/event API).  Streams don't exist on the XLA path — ordering is
owned by the compiler — so Stream/Event are compatibility no-ops that still
give correct synchronize() semantics via jax block_until_ready."""

from __future__ import annotations

import jax

from paddle_tpu._core.place import (  # noqa: F401
    CPUPlace,
    CustomPlace,
    Place,
    TPUPlace,
    device_count,
    get_device,
    is_compiled_with_tpu,
    set_device,
)

__all__ = [
    "set_device",
    "get_device",
    "get_all_device_type",
    "get_available_device",
    "device_count",
    "synchronize",
    "hard_sync",
    "time_step_ms",
    "Stream",
    "Event",
    "current_stream",
    "stream_guard",
    "is_compiled_with_tpu",
    "IS_WINDOWS",
]

IS_WINDOWS = False


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_all_custom_device_type():
    return [t for t in get_all_device_type() if t not in ("cpu", "tpu")]


def synchronize(device=None):
    """Block until all launched device work completes.

    `jax.effects_barrier` / `block_until_ready` resolve at dispatch on
    remote transports (see `hard_sync`), so this additionally enqueues a
    trivial computation per addressable device and reads it back — each
    device executes its stream in order, so the readback implies all
    previously enqueued work finished.
    """
    import jax.numpy as jnp

    jax.effects_barrier()
    for d in jax.local_devices():
        with jax.default_device(d):
            hard_sync(jnp.zeros(8) + 1.0)


def hard_sync(x):
    """TRUE device barrier: read one element of `x` back to the host.

    On some remote PJRT transports (the axon TPU tunnel in this image),
    `jax.block_until_ready` resolves when the dispatch future settles —
    NOT when the device has finished executing — so wall-clock timing
    around it measures dispatch latency, not device time (measured: a
    chain of 8192^3 matmuls "completed" at 40 PFLOPs).  A device→host
    readback is the only barrier that provably waits.  The device runs
    its stream in order, so fetching the last enqueued value implies
    everything enqueued before it has completed.

    Accepts a jax array, a Tensor-like with `._value`, or any pytree;
    syncs on the last leaf and returns `x` unchanged.
    """
    leaf = x._value if hasattr(x, "_value") else x
    device_leaves = [
        l for l in jax.tree_util.tree_leaves(leaf)
        if isinstance(l, jax.Array) and l.size
    ]
    if device_leaves:
        # one element of EVERY device leaf (leaves may live on different
        # devices); host numpy / zero-size leaves must not satisfy the
        # barrier — that silently reverts to the dispatch-only fiction
        jax.device_get([l.ravel()[:1] for l in device_leaves])
    return x


def time_step_ms(fn, args=(), *, inner=10, samples=2):
    """Steady-state per-call wall ms of a compiled step function.

    The public timing primitive for benchmarks: each sample readback-syncs
    (`hard_sync`) batches of `inner` and `2*inner` back-to-back calls and
    differences the totals, so the (large, noisy) transport round trip
    cancels; returns the MIN over `samples` — an RTT noise spike can only
    inflate a sample, so min is the faithful steady-state estimate."""
    from paddle_tpu.ops.autotune import _time_fn

    return min(
        _time_fn(fn, args, warmup=0, iters=1, inner=inner)
        for _ in range(samples)
    )


class Stream:
    """Compatibility stream object; XLA schedules internally."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        jax.effects_barrier()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        jax.effects_barrier()


_current = Stream()


def current_stream(device=None):
    return _current


class stream_guard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *exc):
        return False


class cuda:
    """Namespace shim: the reference exposes paddle.device.cuda.*; here those
    map to the single accelerator's stats."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return _current

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.local_devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.local_devices()[0].memory_stats() or {}
        return stats.get("bytes_in_use", 0)

    @staticmethod
    def max_memory_reserved(device=None):
        stats = jax.local_devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_reserved(device=None):
        stats = jax.local_devices()[0].memory_stats() or {}
        return stats.get("bytes_limit", 0)

    @staticmethod
    def empty_cache():
        pass


# ------------------------------------------------------------- memory stats
# Reference: paddle/fluid/memory/stats.h peak trackers surfaced as
# paddle.device.cuda.max_memory_allocated etc.  TPU-native: PJRT device
# memory_stats plus live-buffer accounting.

def memory_stats(device=None):
    d = jax.devices()[0] if device is None else device
    try:
        return dict(d.memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(device=None):
    st = memory_stats(device)
    if "bytes_in_use" in st:
        return int(st["bytes_in_use"])
    return int(sum(v.nbytes for v in jax.live_arrays()))


def max_memory_allocated(device=None):
    st = memory_stats(device)
    return int(st.get("peak_bytes_in_use", memory_allocated(device)))


def max_memory_reserved(device=None):
    st = memory_stats(device)
    return int(st.get("bytes_reserved", st.get("bytes_limit", 0)))


def empty_cache():
    pass  # XLA/PJRT owns the arena; freeing is GC-driven

from .plugin import (  # noqa: F401,E402
    load_custom_device_plugin,
    registered_custom_devices,
    scan_custom_device_plugins,
)


# ----------------------------------------------------- compile-flag predicates
def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_distribute():
    """Distributed support is built in (jax.distributed + GSPMD)."""
    return True


def is_compiled_with_custom_device(device_type):
    """True when a PJRT plugin backend of this name is registered
    (reference: custom-device runtime query)."""
    import jax

    try:
        return any(d.platform == device_type for d in jax.devices(device_type))
    except RuntimeError:
        return False


def get_available_custom_device():
    """Devices of registered PJRT PLUGIN backends (reference:
    paddle.device.get_available_custom_device) — builtin cpu/tpu are not
    custom devices."""
    import jax

    from .plugin import registered_custom_devices

    out = []
    for plat in registered_custom_devices():
        try:
            out.extend(f"{d.platform}:{d.id}" for d in jax.devices(plat))
        except RuntimeError:
            pass
    return out


def get_cudnn_version():
    """No cuDNN on this backend (reference returns None when not compiled
    with CUDA)."""
    return None


def set_stream(stream=None):
    """Streams are XLA-managed on TPU; accepted for API compat, returns the
    previous (None) stream like the reference's setter contract."""
    return None


class XPUPlace:
    def __init__(self, *a, **k):
        raise RuntimeError("XPU backend is not available in paddle_tpu (TPU-native build)")


class IPUPlace:
    def __init__(self, *a, **k):
        raise RuntimeError("IPU backend is not available in paddle_tpu (TPU-native build)")

__all__ += [
    "is_compiled_with_cuda", "is_compiled_with_rocm", "is_compiled_with_xpu",
    "is_compiled_with_ipu", "is_compiled_with_cinn", "is_compiled_with_distribute",
    "is_compiled_with_custom_device", "get_available_custom_device",
    "get_cudnn_version", "set_stream", "XPUPlace", "IPUPlace",
]
