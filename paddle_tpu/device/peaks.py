"""Per-chip peak bf16 TFLOPs (single source for the benchmark suite's
MFU / vs_baseline math — bench.py, benchmarks/bench_resnet.py,
benchmarks/bench_bert.py)."""

from __future__ import annotations

A100_PEAK_TFLOPS = 312.0  # bf16, the reference baselines' GPU


def device_peak_tflops(device_kind: str, platform: str) -> float:
    """Peak bf16 TFLOPs for a jax device kind; 0.0 for CPU (no MFU)."""
    kind = device_kind.lower()
    if "v6e" in kind or "trillium" in kind:
        return 918.0
    if "v5 lite" in kind or "v5e" in kind:
        return 197.0
    if "v5p" in kind or "v5" in kind:
        return 459.0
    if platform != "cpu":
        return 275.0  # v4 default
    return 0.0
