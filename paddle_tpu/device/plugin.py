"""Pluggable device backends.

Reference: the custom-device C-ABI (paddle/phi/backends/device_ext.h —
DeviceInterface function table covering device/memory/stream/event/
collective hooks) with runtime .so discovery from CUSTOM_DEVICE_ROOT
(paddle/phi/backends/custom/custom_device.cc:1059 LoadCustomRuntimeLib,
device_manager.h:296).

TPU-native redesign: PJRT IS the pluggable-device ABI on the XLA stack — a
vendor backend ships a PJRT plugin .so and every op, allocator, stream and
collective arrives through it, the same coverage device_ext.h enumerates by
hand.  This module is the discovery/registration point: explicit
`load_custom_device_plugin(name, path)` or scanning PADDLE_CUSTOM_DEVICE_ROOT
(CUSTOM_DEVICE_ROOT honored too) for `libpjrt_<name>.so`.
"""

from __future__ import annotations

import os

__all__ = [
    "load_custom_device_plugin",
    "scan_custom_device_plugins",
    "register_custom_backend",
    "registered_custom_devices",
]

_registered: dict[str, str] = {}


def load_custom_device_plugin(name: str, library_path: str, options=None):
    """Register a PJRT plugin as backend `name` (then paddle.set_device(name))."""
    if not os.path.exists(library_path):
        raise FileNotFoundError(f"PJRT plugin library not found: {library_path}")
    from jax._src import xla_bridge

    xla_bridge.register_plugin(name, library_path=library_path, options=options)
    _registered[name] = library_path
    return name


def scan_custom_device_plugins(root=None):
    """Discover `libpjrt_<name>.so` under the plugin root (reference
    CUSTOM_DEVICE_ROOT scan).  Returns the registered backend names."""
    root = root or os.environ.get("PADDLE_CUSTOM_DEVICE_ROOT") or os.environ.get("CUSTOM_DEVICE_ROOT")
    if not root or not os.path.isdir(root):
        return []
    found = []
    for fn in sorted(os.listdir(root)):
        if fn.startswith("libpjrt_") and fn.endswith(".so"):
            name = fn[len("libpjrt_") : -3]
            try:
                load_custom_device_plugin(name, os.path.join(root, fn))
                found.append(name)
            except Exception as e:  # a broken plugin must not kill startup
                import warnings

                warnings.warn(f"custom device plugin {fn}: registration failed: {e}")
    return found


def register_custom_backend(name: str, factory, priority: int = 0):
    """In-process custom backend: register a client factory under `name`
    (the PJRT-plugin flow without a .so — the analog of the reference's
    fake_cpu_device.h test device, test/custom_runtime/
    test_custom_cpu_plugin.py:24).  The backend must also appear in
    jax_platforms (e.g. "cpu,<name>") BEFORE first backend init; then
    `jax.devices(name)` / paddle.set_device(name) target it."""
    from jax._src import xla_bridge

    xla_bridge.register_backend_factory(name, factory, priority=priority, fail_quietly=False)
    _registered[name] = "<in-process factory>"
    return name


def registered_custom_devices():
    return dict(_registered)
