"""Eager autograd engine: a vjp tape.

Capability equivalent of the reference's eager autograd
(paddle/fluid/eager/backward.cc:105 RunBackward, grad_node_info.h:197
GradNodeBase, grad_tensor_holder.h) re-designed for XLA:

- Instead of per-op handwritten GradNode classes generated from backward.yaml,
  every differentiable op call goes through `apply(name, fn, *args)`, which
  uses jax.vjp to execute the forward ONCE and capture a reusable backward
  closure holding on-device residuals.  That closure *is* the grad node.
- `backward_from` replicates the reference's dual-queue dependency-counted
  walk (backward.cc:24-65 in-degree computation, :126-165 queue loop) over
  these nodes, accumulating cotangents per node output (GradTensorHolder
  equivalent) and writing leaf grads into Tensor.grad
  (GradNodeAccumulation equivalent).
- Because jax.vjp composes with tracing, the same tape works inside jax.jit:
  a whole train step written imperatively (forward, loss.backward(),
  opt.step()) can be traced and compiled end-to-end — the TPU answer to the
  reference's C++ hot path.
"""

from __future__ import annotations

import contextlib
import threading
import weakref
from collections import deque

import jax
import jax.numpy as jnp

from .tensor import Tensor
from . import dispatch
from . import flags

__all__ = [
    "apply",
    "backward_from",
    "backward_multi",
    "grad",
    "no_grad",
    "enable_grad",
    "set_grad_enabled",
    "is_grad_enabled",
]


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True
        self.touch_recorders = []  # stack of lists capturing Tensor inputs


_state = _GradState()


class TouchRecorder:
    """Collects op-input Tensors (and the ids of Tensors CREATED meanwhile,
    so callers can filter out branch-local intermediates)."""

    def __init__(self):
        self.inputs: list = []
        self.created: set = set()

    def external_inputs(self):
        out, seen = [], set()
        for t in self.inputs:
            if id(t) not in seen and id(t) not in self.created:
                seen.add(id(t))
                out.append(t)
        return out


@contextlib.contextmanager
def record_touched_tensors(rec: "TouchRecorder"):
    """Record every Tensor that flows into an op while active (used by
    control-flow capture to discover closure-captured inputs)."""
    _state.touch_recorders.append(rec)
    try:
        yield rec
    finally:
        _state.touch_recorders.pop()
_static_prog_mod = None  # lazy ref to paddle_tpu.static.program (capture hook)
_profiler_mod = None  # lazy ref to paddle_tpu.profiler (host event hook)


def is_grad_enabled() -> bool:
    return _state.enabled


def set_grad_enabled(enabled: bool):
    _state.enabled = bool(enabled)


@contextlib.contextmanager
def no_grad():
    prev = _state.enabled
    _state.enabled = False
    try:
        yield
    finally:
        _state.enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _state.enabled
    _state.enabled = True
    try:
        yield
    finally:
        _state.enabled = prev


class GradNode:
    """One recorded op: backward closure + graph edges.

    Mirrors GradNodeBase (reference grad_node_info.h:197): `inputs` are the
    next edges, `out_avals` the shapes/dtypes of this op's forward outputs
    (needed to materialize zero cotangents for unused outputs).
    """

    __slots__ = (
        "name",
        "vjp_fn",
        "inputs",
        "out_avals",
        "out_tree",
        "n_outputs",
        "out_refs",
        "released",
        "rebuild",
        "taped_vjp",
        "__weakref__",
    )

    def __init__(self, name, vjp_fn, inputs, out_avals, out_tree):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # list[Tensor] — differentiable inputs, vjp order
        self.out_avals = out_avals  # list[(shape, dtype)]
        self.out_tree = out_tree
        self.n_outputs = len(out_avals)
        self.out_refs = []  # list[weakref to output Tensors], for hooks
        self.released = False
        # (fn, fixed_vals, diff_set, n_args, kwargs, input_snapshot): enough
        # to re-run the forward under jax.vjp with the cotangents as EXTRA
        # differentiable inputs — the create_graph=True path (double
        # backward; reference builds generated double-grad nodes,
        # python/paddle/base/dygraph/base.py:645).  input_snapshot holds the
        # record-time values so in-place mutation between forward and the
        # create_graph walk is detected, not silently recomputed-over.
        self.rebuild = None
        # create_graph path for CUSTOM-backward nodes (PyLayer): a callable
        # (cot_tensors) -> input grads running the user backward WITH grad
        # recording — autodiffing the forward would be wrong for e.g.
        # straight-through estimators.
        self.taped_vjp = None

    def release(self):
        self.vjp_fn = None
        self.rebuild = None
        self.released = True


def _maybe_amp_cast(name, args):
    """AMP O1 cast hook (reference: AMP logic in generated ad_funcs,
    paddle/fluid/eager/amp_utils.h): white-listed ops run in the low dtype,
    black-listed ops in float32, others follow their inputs."""
    try:
        from paddle_tpu import amp as amp_mod
    except ImportError:
        return args
    st = amp_mod.amp_state()
    if not st.enabled:
        return args
    if name in amp_mod.white_list():
        target = st.dtype
    elif name in amp_mod.black_list():
        target = jnp.float32
    else:
        return args

    def cast(a):
        if isinstance(a, Tensor) and jnp.issubdtype(a._value.dtype, jnp.floating):
            if a._value.dtype != target:
                if a.stop_gradient or not _state.enabled:
                    return Tensor(a._value.astype(target))
                # grad-carrying tensors cast through the tape so the cotangent
                # is cast back on the way down
                return apply("amp_cast", lambda v: v.astype(target), a)
        return a

    return tuple(cast(a) for a in args)


def _nanfail(ok, name):
    if not bool(ok):
        raise FloatingPointError(f"NaN/Inf detected in output of op '{name}'")


def _check_nan_inf(name, vals):
    """FLAGS_check_nan_inf: eager values checked synchronously; traced values
    get an in-graph host callback so the check ALSO fires inside compiled
    steps (reference runs it in-kernel, paddle/phi/kernels/
    check_numerics_kernel.h — round-1 skipped tracers, making the flag dead
    on the only path that matters)."""
    import functools as _ft

    for v in vals:
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            if isinstance(v, jax.core.Tracer):
                jax.debug.callback(_ft.partial(_nanfail, name=name), jnp.all(jnp.isfinite(v)))
            elif bool(jnp.any(~jnp.isfinite(v))):
                raise FloatingPointError(f"NaN/Inf detected in output of op '{name}'")


def apply(name, fn, *args, n_outputs=None, **kwargs):
    """Profiler/static-capture wrapper around the eager funnel; see
    _apply_impl for the semantics."""
    global _static_prog_mod, _profiler_mod
    if _static_prog_mod is None:
        try:
            from paddle_tpu.static import program as _spm

            _static_prog_mod = _spm
        except ImportError:
            _static_prog_mod = False
    if _static_prog_mod and _static_prog_mod.in_static_capture():
        return _static_prog_mod.current_main_program().record(name, fn, args, kwargs)

    if _profiler_mod is None:
        try:
            from paddle_tpu import profiler as _pm

            _profiler_mod = _pm
        except ImportError:
            _profiler_mod = False
    if _profiler_mod and _profiler_mod._active_profiler is not None:
        with _profiler_mod.RecordEvent(f"op::{name}"):
            return _apply_impl(name, fn, *args, n_outputs=n_outputs, **kwargs)
    return _apply_impl(name, fn, *args, n_outputs=n_outputs, **kwargs)


def _apply_impl(name, fn, *args, n_outputs=None, **kwargs):
    """Execute op `fn` over Tensor/raw args, recording a grad node if needed.

    fn receives raw jax values positionally (same order as args) and must
    return a jax value or a tuple/list of them.  kwargs are static.
    Non-Tensor args and stop_gradient Tensors are closed over (not
    differentiated).  Integer/bool outputs never require grad.

    Inside a static program_guard the `apply` wrapper records an Operator
    instead of executing — the whole op surface is static-capturable for free
    (the reference gets the same dual-mode from its YAML codegen emitting
    both dygraph ad_funcs and PIR ops).

    With FLAGS_eager_op_jit on, repeated calls with the same signature route
    through the dispatch cache (_core.dispatch): the no-grad path runs a
    cached jax.jit of fn, the grad path a cached jitted jax.vjp pair — the
    per-op Python retrace cost is paid once per signature, not per call.
    """
    args = _maybe_amp_cast(name, args)
    tensors = [a for a in args if isinstance(a, Tensor)]
    if _state.touch_recorders:
        # append raw; consumers dedupe by id() (Tensor __eq__ is elementwise)
        _state.touch_recorders[-1].inputs.extend(tensors)
    needs_grad = _state.enabled and any(not t.stop_gradient for t in tensors)

    handle = (dispatch.lookup(name, fn, args, kwargs, needs_grad)
              if flags.flag("FLAGS_eager_op_jit") else None)

    if not needs_grad:
        out = dispatch.FALLBACK
        if handle is not None and handle.hit:
            out = handle.call_nograd()
        if out is dispatch.FALLBACK:
            vals = [a._value if isinstance(a, Tensor) else a for a in args]
            out = fn(*vals, **kwargs)
            if handle is not None and not handle.hit:
                handle.record(out)
        if flags.flag("FLAGS_check_nan_inf"):
            _check_nan_inf(name, jax.tree_util.tree_leaves(out))

        def _mk(v):
            t = Tensor(v, stop_gradient=True)
            if _state.touch_recorders:
                for rec in _state.touch_recorders:
                    rec.created.add(id(t))
            return t

        return jax.tree_util.tree_map(
            _mk, out, is_leaf=lambda x: not isinstance(x, (tuple, list, dict))
        )

    # Partition: differentiable (float tensors with stop_gradient=False) vs closed-over.
    diff_idx = []
    for i, a in enumerate(args):
        if isinstance(a, Tensor) and not a.stop_gradient and jnp.issubdtype(
            jnp.asarray(a._value).dtype if not hasattr(a._value, "dtype") else a._value.dtype,
            jnp.inexact,
        ):
            diff_idx.append(i)
    diff_tensors = [args[i] for i in diff_idx]
    diff_set = set(diff_idx)
    fixed_vals = [None if i in diff_set else (a._value if isinstance(a, Tensor) else a) for i, a in enumerate(args)]

    res = dispatch.FALLBACK
    if handle is not None and handle.hit:
        res = handle.call_grad(diff_idx)
    if res is not dispatch.FALLBACK:
        out, vjp_fn = res
    else:
        def g(*diff_vals):
            it = iter(diff_vals)
            full = [next(it) if i in diff_set else fixed_vals[i] for i in range(len(args))]
            return fn(*full, **kwargs)

        out, vjp_fn = jax.vjp(g, *(t._value for t in diff_tensors))
        if handle is not None and not handle.hit:
            handle.record(out)
    flat_out, out_tree = jax.tree_util.tree_flatten(out)
    if flags.flag("FLAGS_check_nan_inf"):
        _check_nan_inf(name, flat_out)
    out_avals = [(v.shape, v.dtype) for v in flat_out]
    node = GradNode(name, vjp_fn, diff_tensors, out_avals, out_tree)
    node.rebuild = (fn, fixed_vals, diff_set, len(args), kwargs,
                    tuple(t._value for t in diff_tensors))

    out_tensors = []
    for i, v in enumerate(flat_out):
        is_float = jnp.issubdtype(v.dtype, jnp.inexact)
        t = Tensor(v, stop_gradient=not is_float)
        if is_float:
            t._grad_node = node
            t._out_index = i
        out_tensors.append(t)
        node.out_refs.append(weakref.ref(t))
    if _state.touch_recorders:
        for rec in _state.touch_recorders:
            rec.created.update(id(t) for t in out_tensors)
    return jax.tree_util.tree_unflatten(out_tree, out_tensors)


# --------------------------------------------------------------------- engine


def _accumulate(holder, idx, val):
    cur = holder[idx]
    holder[idx] = val if cur is None else cur + val


def backward_from(root: Tensor, grad_tensor=None, retain_graph: bool = False):
    if grad_tensor is None:
        if root.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires an explicit grad_tensor"
            )
        grad_val = jnp.ones_like(root._value)
    else:
        grad_val = grad_tensor._value if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
    backward_multi([root], [grad_val], retain_graph)


def backward_multi(roots, grad_vals, retain_graph: bool = False):
    """Dependency-counted reverse walk (reference backward.cc:105)."""
    with no_grad():
        _backward_impl(roots, grad_vals, retain_graph, leaf_targets=None)


def _reachable_graph(root_nodes, create_graph=False):
    """BFS the node graph; return set of nodes + in-degree (number of consumer
    nodes whose vjp contributes cotangents into this node).

    Normal mode stops at released nodes (their outputs act as leaves, the
    long-standing partial-backward boundary); create_graph mode keeps them so
    the walk raises the clear already-released error instead of silently
    truncating the second-order graph."""
    seen = set()
    indeg = {}
    q = deque(root_nodes)
    for n in root_nodes:
        seen.add(n)
        indeg.setdefault(n, 0)
    while q:
        node = q.popleft()
        for t in node.inputs:
            child = t._grad_node
            if child is not None and (create_graph or not child.released):
                indeg[child] = indeg.get(child, 0) + 1
                if child not in seen:
                    seen.add(child)
                    q.append(child)
    return seen, indeg


def _run_hooks(tensor, grad_val):
    """Type-preserving: raw in → raw out; Tensor in (create_graph walk) →
    Tensor out, so hook results stay on the tape."""
    as_tensor = isinstance(grad_val, Tensor)
    for hook in list(tensor._hooks):
        res = hook(grad_val if as_tensor else Tensor(grad_val))
        if res is not None:
            if as_tensor:
                grad_val = res if isinstance(res, Tensor) else Tensor(res)
            else:
                grad_val = res._value if isinstance(res, Tensor) else res
    return grad_val


def _vjp_through_tape(node, cot_tensors):
    """Compute node's input cotangents THROUGH the tape (create_graph=True).

    Re-runs the recorded forward under jax.vjp inside `apply`, with both the
    original differentiable inputs and the incoming cotangents as
    differentiable inputs of a new '<name>_grad' node — so the returned
    grads carry grad nodes and support another backward() (the reference's
    generated double-grad GradNodes, e.g. MatmulDoubleGradNode).  Costs one
    forward recompute per node, the standard higher-order trade.
    """
    if node.released or node.rebuild is None:
        raise RuntimeError(
            f"Grad node '{node.name}' already released; pass retain_graph=True "
            "to the earlier backward()/grad() call to differentiate through "
            "this graph again."
        )
    fn, fixed_vals, diff_set, n_args, kwargs, snapshot = node.rebuild
    for t, snap in zip(node.inputs, snapshot):
        if t._value is not snap:
            raise RuntimeError(
                f"an input of op '{node.name}' needed for create_graph=True "
                "has been modified by an in-place operation since it was "
                "recorded"
            )
    k = len(node.inputs)

    def vjp_apply(*vals):
        diff_vals, cot_flat = vals[:k], vals[k:]

        def g(*dv):
            it = iter(dv)
            full = [next(it) if i in diff_set else fixed_vals[i] for i in range(n_args)]
            return fn(*full, **kwargs)

        _, vjp_fn = jax.vjp(g, *diff_vals)
        cot = jax.tree_util.tree_unflatten(node.out_tree, list(cot_flat))
        return tuple(vjp_fn(cot))

    outs = apply(f"{node.name}_grad", vjp_apply, *node.inputs, *cot_tensors)
    return list(outs) if isinstance(outs, (tuple, list)) else [outs]


def _backward_impl(roots, grad_vals, retain_graph, leaf_targets,
                   create_graph=False, boundary_ids=()):
    """If leaf_targets is not None: return grads for those tensors instead of
    writing .grad (used by paddle.grad).

    With create_graph=True every cotangent in flight is a Tensor and every
    vjp runs through `apply` (see _vjp_through_tape), so the returned grads
    are themselves differentiable."""
    holders = {}  # node -> list of cotangent values per output
    root_nodes = []
    leaf_grads = {}  # id(tensor) -> value (for leaf_targets mode)
    target_ids = {id(t) for t in leaf_targets} if leaf_targets is not None else None

    def _record_target(t, g):
        leaf_grads[id(t)] = g if id(t) not in leaf_grads else leaf_grads[id(t)] + g

    for root, gval in zip(roots, grad_vals):
        node = root._grad_node
        if node is None:
            # Root is a leaf: its grad is the seed itself.
            if not root.stop_gradient:
                gval = _run_hooks(root, gval)
                if leaf_targets is None:
                    _acc_tensor_grad(root, gval)
                else:
                    leaf_grads[id(root)] = (
                        gval if id(root) not in leaf_grads else leaf_grads[id(root)] + gval
                    )
            continue
        if node not in holders:
            holders[node] = [None] * node.n_outputs
            root_nodes.append(node)
        _accumulate(holders[node], root._out_index, gval)

    if not root_nodes:
        return leaf_grads

    nodes, indeg = _reachable_graph(root_nodes, create_graph=create_graph)
    ready = deque(n for n in nodes if indeg.get(n, 0) == 0)
    processed = set()

    while ready:
        node = ready.popleft()
        if node in processed:
            continue
        processed.add(node)
        cots = holders.get(node, [None] * node.n_outputs)
        full = []
        for i, (shape, dt) in enumerate(node.out_avals):
            v = cots[i]
            if v is None:
                v = Tensor(jnp.zeros(shape, dt)) if create_graph else jnp.zeros(shape, dt)
            else:
                ref = node.out_refs[i]() if i < len(node.out_refs) else None
                if ref is not None and ref._hooks:
                    v = _run_hooks(ref, v)
            full.append(v)
        if create_graph:
            if node.taped_vjp is not None:
                in_grads = node.taped_vjp(full)
            else:
                in_grads = _vjp_through_tape(node, full)
        else:
            cot_struct = jax.tree_util.tree_unflatten(node.out_tree, full)
            if node.released or node.vjp_fn is None:
                raise RuntimeError(
                    f"Grad node '{node.name}' already released; pass retain_graph=True "
                    "to backward() to backprop twice through the same graph."
                )
            in_grads = node.vjp_fn(cot_struct)
        # An explicit retain_graph=False releases even under create_graph:
        # the grad-of-grad nodes built by _vjp_through_tape carry their own
        # closures, so the first-order residuals can be freed.
        if not retain_graph:
            node.release()

        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            if id(t) in boundary_ids:
                # no_grad_set: this tensor receives no gradient and blocks
                # propagation into its producers (reference
                # python/paddle/base/dygraph/base.py grad no_grad_vars)
                child = t._grad_node
                if child is not None and child in indeg:
                    indeg[child] -= 1
                    if indeg[child] == 0:
                        ready.append(child)
                continue
            if getattr(g, "dtype", None) is not None and g.dtype == jax.dtypes.float0:
                continue
            child = t._grad_node
            if child is None or (child not in nodes):
                if not t.stop_gradient:
                    g = _run_hooks(t, g)
                    if leaf_targets is None:
                        _acc_tensor_grad(t, g)
                    else:
                        _record_target(t, g)
            else:
                if target_ids is not None and id(t) in target_ids:
                    _record_target(t, _run_hooks(t, g))
                if child not in holders:
                    holders[child] = [None] * child.n_outputs
                _accumulate(holders[child], t._out_index, g)
                indeg[child] -= 1
                if indeg[child] == 0:
                    ready.append(child)
    return leaf_grads


def _acc_tensor_grad(t: Tensor, g):
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    elif not hasattr(t.grad, "_value"):
        # a SelectedRows sparse grad already accumulated here (sparse
        # Embedding hook) now meets a dense contribution: densify
        t.grad = Tensor(t.grad.accumulate(g), stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._value + g, stop_gradient=True)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph: bool = False,
    only_inputs: bool = True,
    allow_unused: bool = False,
    no_grad_vars=None,
):
    """paddle.grad equivalent (reference python/paddle/base/dygraph/base.py:615;
    create_graph=True builds the double-backward graph like the reference's
    generated double-grad nodes — see _vjp_through_tape)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_vals = [jnp.ones_like(o._value) for o in outputs]
    else:
        grad_outputs = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs]
        grad_vals = [
            jnp.ones_like(o._value) if g is None else (g._value if isinstance(g, Tensor) else jnp.asarray(g))
            for o, g in zip(outputs, grad_outputs)
        ]
    # Reference semantics: retain_graph defaults to create_graph.
    retain = bool(retain_graph) if retain_graph is not None else bool(create_graph)
    boundary = {id(t) for t in (no_grad_vars or ())}
    if create_graph:
        # Cotangents must ride the tape: seed with Tensors (a grad_outputs
        # Tensor keeps its own grad node so grads can flow into it too) and
        # walk with grad recording ON.
        seeds = []
        for gv, go in zip(
            grad_vals, grad_outputs if grad_outputs is not None else [None] * len(grad_vals)
        ):
            seeds.append(go if isinstance(go, Tensor) else Tensor(gv))
        with enable_grad():
            leaf_grads = _backward_impl(
                outputs, seeds, retain, leaf_targets=inputs, create_graph=True,
                boundary_ids=boundary,
            )
    else:
        with no_grad():
            leaf_grads = _backward_impl(outputs, grad_vals, retain,
                                        leaf_targets=inputs,
                                        boundary_ids=boundary)
    results = []
    for t in inputs:
        g = leaf_grads.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; pass allow_unused=True"
                )
            results.append(None)
        elif isinstance(g, Tensor):
            results.append(g)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results
