"""Dtype system.

Mirrors the reference's dtype surface (paddle.float32 etc.; reference:
paddle/phi/common/data_type.h, python/paddle/framework/dtype.py) but is a thin
veneer over numpy/jax dtypes — on TPU the canonical compute dtype is bfloat16
and XLA owns layout, so no DataLayout/LoD machinery is reproduced.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = [
    "DType",
    "dtype",
    "bool_",
    "uint8",
    "int8",
    "int16",
    "int32",
    "int64",
    "float16",
    "bfloat16",
    "float32",
    "float64",
    "complex64",
    "complex128",
    "float8_e4m3fn",
    "float8_e5m2",
    "to_jax_dtype",
    "to_paddle_dtype",
    "is_floating_dtype",
    "is_integer_dtype",
    "is_complex_dtype",
    "promote_types",
]


class DType:
    """A framework dtype: hashable, comparable with strings and numpy dtypes."""

    __slots__ = ("name", "np_dtype", "itemsize")

    _registry: dict = {}

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        self.itemsize = self.np_dtype.itemsize
        DType._registry[name] = self

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __str__(self):
        return self.name

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            other_norm = _STR_ALIASES.get(other, other)
            return self.name == other_norm
        try:
            return self.np_dtype == np.dtype(other)
        except TypeError:
            return NotImplemented

    def __ne__(self, other):
        result = self.__eq__(other)
        if result is NotImplemented:
            return result
        return not result

    @property
    def is_floating_point(self) -> bool:
        return is_floating_dtype(self)

    @property
    def is_complex(self) -> bool:
        return is_complex_dtype(self)


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", jnp.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
float8_e4m3fn = DType("float8_e4m3fn", jnp.float8_e4m3fn)
float8_e5m2 = DType("float8_e5m2", jnp.float8_e5m2)

_STR_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
    "bfloat": "bfloat16",
}

_FLOATING = {"float16", "bfloat16", "float32", "float64", "float8_e4m3fn", "float8_e5m2"}
_INTEGER = {"uint8", "int8", "int16", "int32", "int64"}
_COMPLEX = {"complex64", "complex128"}


def dtype(obj) -> DType:
    """Coerce a string / numpy dtype / DType into a DType."""
    return to_paddle_dtype(obj)


def to_paddle_dtype(obj) -> DType:
    if isinstance(obj, DType):
        return obj
    if isinstance(obj, str):
        name = _STR_ALIASES.get(obj, obj)
        if name in DType._registry:
            return DType._registry[name]
        raise ValueError(f"Unknown dtype string: {obj!r}")
    np_dt = np.dtype(obj)
    for dt in DType._registry.values():
        if dt.np_dtype == np_dt:
            return dt
    raise ValueError(f"Unsupported dtype: {obj!r}")


# TPU-native width policy: 64-bit dtypes exist on the API surface (paddle
# parity) but compute in their 32-bit widths — TPU has no f64 and emulates
# i64, and jax runs without x64 (see _core/__init__.py).  The mapping is done
# here, at the single jax boundary, so no "explicitly requested dtype int64"
# warnings and no accidental 64-bit values reach XLA or Mosaic.
_JAX_NARROW = {
    "int64": np.dtype(np.int32),
    "float64": np.dtype(np.float32),
    "complex128": np.dtype(np.complex64),
}


def to_jax_dtype(obj):
    """Coerce to a numpy dtype usable by jax.numpy (64-bit narrowed to 32)."""
    if obj is None:
        return None
    dt = to_paddle_dtype(obj)
    return _JAX_NARROW.get(dt.name, dt.np_dtype)


def is_floating_dtype(dt) -> bool:
    return to_paddle_dtype(dt).name in _FLOATING


def is_integer_dtype(dt) -> bool:
    return to_paddle_dtype(dt).name in _INTEGER


def is_complex_dtype(dt) -> bool:
    return to_paddle_dtype(dt).name in _COMPLEX


def promote_types(a, b) -> DType:
    return to_paddle_dtype(jnp.promote_types(to_jax_dtype(a), to_jax_dtype(b)))
