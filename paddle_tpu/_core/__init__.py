"""Core runtime init.

Dtype-width policy (TPU-native): 64-bit types are NOT enabled.  TPU has no
f64 ALU and emulates i64; worse, with jax_enable_x64 the Mosaic kernel
lowerer itself re-traces helper functions under the global flag and emits
64->32-bit converts that its own conversion helper cannot lower (infinite
recursion — observed on real v5e, see tests/test_ops_pallas.py's jaxpr
scan).  Paddle's int64/float64 dtype *names* remain on the API surface for
parity (reference: python/paddle/framework/dtype.py) but map to their 32-bit
widths at the jax boundary (_core/dtype.py:to_jax_dtype).
"""

from . import autograd, compile_cache, dtype, flags, place, random, tensor  # noqa: F401
