import jax as _jax

# Paddle dtype semantics: int64 creation defaults, float64 available.  XLA
# still computes the hot path in bf16/f32 (models pass explicit dtypes);
# x64 here is about API parity, not compute width.
_jax.config.update("jax_enable_x64", True)

from . import autograd, dtype, flags, place, random, tensor  # noqa: F401
