"""Global flag registry.

Equivalent of the reference's exported-flags system (paddle/phi/core/flags.h:141,
paddle.get_flags/set_flags) with env-var override (FLAGS_*), minus the C++
gflags machinery — a process-wide Python registry is the right weight here.
"""

from __future__ import annotations

import os
from typing import Any

__all__ = ["define_flag", "get_flags", "set_flags", "flag", "on_change"]

_FLAGS: dict[str, dict[str, Any]] = {}

# Callbacks fired after every set_flags() with the list of changed flag
# names.  The eager dispatch cache registers one: op bodies may read flags
# at trace time, so any flag change must invalidate cached traces.
_listeners: list = []


def on_change(callback):
    """Register `callback(changed_names)` to run after each set_flags()."""
    _listeners.append(callback)
    return callback


def _coerce(value, default):
    if isinstance(default, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if isinstance(default, int):
        return int(value)
    if isinstance(default, float):
        return float(value)
    return value


def define_flag(name: str, default, help_str: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    env = os.environ.get(name)
    value = _coerce(env, default) if env is not None else default
    _FLAGS[name] = {"value": value, "default": default, "help": help_str}
    return value


def flag(name: str):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    return _FLAGS[name]["value"]


def get_flags(flags=None) -> dict:
    if flags is None:
        return {k: v["value"] for k, v in _FLAGS.items()}
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        key = name if name.startswith("FLAGS_") else "FLAGS_" + name
        out[name] = _FLAGS[key]["value"]
    return out


def set_flags(flags: dict):
    changed = []
    for name, value in flags.items():
        key = name if name.startswith("FLAGS_") else "FLAGS_" + name
        if key not in _FLAGS:
            define_flag(key, value)
        else:
            new = _coerce(value, _FLAGS[key]["default"])
            if new == _FLAGS[key]["value"]:
                continue  # no-op re-set: don't invalidate listeners' caches
            _FLAGS[key]["value"] = new
        changed.append(key)
    if changed:
        for cb in list(_listeners):
            cb(changed)


# Core flags (subset of the reference's 71 exported flags that are meaningful on TPU).
define_flag("FLAGS_check_nan_inf", False, "Scan op outputs for NaN/Inf in eager mode")
define_flag("FLAGS_default_dtype", "float32", "Default floating dtype for creation ops")
define_flag("FLAGS_tpu_matmul_precision", "default", "jax matmul precision: default|high|highest")
define_flag("FLAGS_eager_op_jit", True, "Route eager composite ops through cached jax.jit")
define_flag(
    "FLAGS_eager_op_cache_size",
    1024,
    "Max entries in the eager dispatch fast-path cache (LRU; see _core.dispatch)",
)
define_flag(
    "FLAGS_scan_layers",
    False,
    "Force nn.LayerStack scan-over-layers for models with a fuse_layer_stack "
    "config knob (depth-constant trace/compile; models/llama.py, models/gpt.py)",
)
define_flag(
    "FLAGS_decode_chunk",
    8,
    "Macro-step decode width D: paged decode advances D tokens per compiled "
    "dispatch (lax.scan inside the jitted step; token streams bit-identical "
    "for every D).  Consumed by LlamaForCausalLM.generate and "
    "serving.GenerationEngine; 1 = per-token dispatch",
)
define_flag(
    "FLAGS_prefill_chunk_blocks",
    0,
    "Per-macro-step prefill budget for interleaved chunked prefill, in pool "
    "blocks: each serving step() runs at most this many block-sized prefill "
    "chunks before the decode dispatch (deadline pressure may double it; "
    "serving.GenerationEngine).  0 = atomic prefill at admission (legacy)",
)
define_flag(
    "FLAGS_preempt_low_priority",
    True,
    "Allow the serving admission scheduler to preempt LOW-priority requests "
    "when a higher-priority request cannot be admitted: their pool pages are "
    "parked host-side and the stream resumes bit-identically on re-admission "
    "(submit-time nonces; serving.GenerationEngine)",
)
define_flag(
    "FLAGS_compilation_cache_dir",
    "",
    "Directory for JAX's persistent XLA compilation cache: warm process "
    "starts reload compiled steps from disk (_core.compile_cache)",
)
define_flag(
    "FLAGS_use_pallas_fusion",
    True,
    "Substitute attention/rms-norm/swiglu subgraphs in captured Programs "
    "with Pallas kernels before lowering (static.rewrite.PallasFusionPass)",
)
define_flag(
    "FLAGS_verify_programs",
    False,
    "Verify-mode for the static IR (static/verify.py): ProgramVerifier runs "
    "around every program pass and on the Executor's compile path, and "
    "rewritten programs are differentially replayed against the original "
    "on the live feed (docs/VERIFIER.md)",
)
define_flag(
    "FLAGS_checkpoint_kill_point",
    "",
    "Dev-mode fault injection for the checkpoint commit protocol: the "
    "process SIGKILLs itself when CheckpointManager reaches this named "
    "point (after-shard-write | before-manifest | mid-manifest | "
    "after-commit) — crash consistency is tested mechanically "
    "(distributed/checkpoint/manager.py, docs/CHECKPOINT.md)",
)
define_flag(
    "FLAGS_checkpoint_verify_on_save",
    False,
    "Belt-and-braces: re-read and checksum-verify a checkpoint directory "
    "immediately after its atomic commit (CheckpointManager; the write "
    "thread raises on mismatch instead of letting a bad checkpoint be "
    "discovered at restore time)",
)
define_flag(
    "FLAGS_prefix_cache",
    False,
    "Radix/prefix KV reuse in serving.GenerationEngine: admission matches "
    "the longest cached token-id prefix at page granularity and takes "
    "references to those pool pages instead of re-prefilling them; full "
    "prompt blocks written by prefill are inserted back into the tree and "
    "refcount-zero leaves are evicted LRU under pool pressure "
    "(docs/DECODE.md)",
)
define_flag(
    "FLAGS_kv_cache_dtype",
    "bf16",
    "Paged-KV pool storage dtype for serving.GenerationEngine: 'bf16' "
    "(default) keeps full-precision pools in the model's serving dtype; "
    "'int8' stores quantized values with per-block-per-head scales carried "
    "alongside the pool and dequantized on gather inside the jitted decode "
    "step — roughly double the resident requests at fixed pool bytes "
    "(ops/paged_attention.QuantPool, docs/DECODE.md)",
)
define_flag(
    "FLAGS_schedule_search",
    False,
    "Cost-model-driven Pallas schedule search over discovered reduction-/"
    "matmul-rooted subgraphs (static/schedule_search.py): enumerate "
    "candidate tilings, prune by roofline + VMEM budget, measure the "
    "survivors, and substitute only schedules that beat XLA by the "
    "measured-win margin — losing subgraphs persist as disabled in the "
    "per-device autotune cache (docs/SCHEDULE_SEARCH.md)",
)
define_flag(
    "FLAGS_schedule_search_budget",
    6,
    "Max schedule candidates measured on device per discovered subgraph "
    "(the top-K survivors of the roofline + VMEM prunes); tests pin this "
    "low to bound tier-1 wall time",
)
define_flag(
    "FLAGS_schedule_search_min_win",
    1.05,
    "Measured-win gate margin: a searched Pallas schedule must beat the "
    "XLA-only twin by at least this ratio or the subgraph is recorded as "
    "disabled for this device kind and never re-measured",
)
define_flag(
    "FLAGS_schedule_search_decode",
    True,
    "With FLAGS_schedule_search on, also point the searcher at the serving "
    "engine's decode hot chain (paged gather -> dequant -> sdpa core -> "
    "quant-write; ops/decode_chain.py): the compiled macro-step consumes an "
    "accepted per-device-kind schedule, TP-sharded engines skip with a "
    "counted telemetry skip.  Off = Program-level search only "
    "(docs/SCHEDULE_SEARCH.md phase 2)",
)
define_flag(
    "FLAGS_verify_sharding",
    False,
    "Mesh lint for the distributed tier (static/mesh_lint.py): statically "
    "analyze sharded computations — placement/axis congruence, collective "
    "participation (incl. data-dependent-predicate collectives, the "
    "deadlock/SIGSEGV class), use-after-donation, per-device HBM "
    "estimates — around program passes, on the Executor's compile path, "
    "and when TrainStep/ShardedTrainStep/GenerationEngine build "
    "(docs/MESH_LINT.md).  Same contract as FLAGS_verify_programs: no "
    "device collective is ever launched by the analysis",
)
define_flag(
    "FLAGS_mesh_lint_replicated_mb",
    8.0,
    "Mesh-lint threshold (MiB): a tensor at least this large that ends up "
    "fully replicated on a multi-device mesh is flagged as "
    "replicated-giant with its per-device byte cost (static/mesh_lint.py)",
)
define_flag(
    "FLAGS_mesh_lint_hbm_budget_gb",
    0.0,
    "Mesh-lint per-device HBM budget (GiB; 0 disables): the estimated "
    "sharding-divided bytes per device (params + optimizer state + KV "
    "pools) above this raises an over-budget violation "
    "(static/mesh_lint.py, docs/MESH_LINT.md)",
)
define_flag(
    "FLAGS_lora_max_adapters",
    8,
    "Usable adapter slots in a serving AdapterPack (nn/lora.py): a "
    "GenerationEngine built with adapters= pre-allocates this many "
    "hot-swappable LoRA slots PLUS the reserved slot 0 (the zero-adapter "
    "base-model identity).  Geometry is fixed at engine construction — "
    "register_adapter/evict_adapter mutate slot contents only, so "
    "compiled decode steps never recompile on a swap (docs/LORA.md)",
)
define_flag(
    "FLAGS_engine_snapshot_dir",
    "",
    "Serving fault tolerance (serving/snapshot.py, docs/CHECKPOINT.md): "
    "directory for live GenerationEngine snapshots.  When set, "
    "engine.step() calls maybe_snapshot() at every macro-step boundary — "
    "a pending SIGTERM preemption flag (install_preemption_handler) or "
    "the FLAGS_engine_snapshot_interval period then commits a restorable "
    "snapshot through the SAME atomic rename protocol as "
    "CheckpointManager.  Empty disables the automatic path (explicit "
    "engine.snapshot(dir)/drain(dir) calls still work)",
)
define_flag(
    "FLAGS_engine_snapshot_interval",
    0,
    "Macro-steps between periodic live-engine snapshots "
    "(FLAGS_engine_snapshot_dir must be set; 0 = preemption-triggered "
    "only).  Snapshots are written at macro-step boundaries, never "
    "mid-dispatch — the serving mirror of CheckpointManager's "
    "save_interval_steps (serving/snapshot.py)",
)
define_flag(
    "FLAGS_cluster_heartbeat_ms",
    100,
    "Disaggregated serving cluster (serving/cluster.py, "
    "docs/SERVING_CLUSTER.md): heartbeat period — every worker bumps its "
    "TCPStore counter twice per period from a background thread, and the "
    "router's failure detector counts elapsed periods without an advance "
    "as misses",
)
define_flag(
    "FLAGS_cluster_heartbeat_misses",
    30,
    "Miss threshold of the cluster failure detector: a replica whose "
    "heartbeat counter has not advanced for this many consecutive "
    "FLAGS_cluster_heartbeat_ms periods is declared dead — its prefix "
    "pages leave the cluster index and its accepted-but-unfinished "
    "requests re-dispatch (serving/cluster.py)",
)
define_flag(
    "FLAGS_cluster_standby",
    0,
    "Warm standby tier of the disaggregated serving cluster "
    "(serving/cluster.py): EngineCluster pre-forks this many standby "
    "worker processes that have already paid jax import + trace + "
    "persistent-cache-served compile against the cluster's engine "
    "geometry.  On a detected decode-replica death, promotion hands a "
    "warm standby the dead replica's snapshot dir and re-keys its rings "
    "into the replica slot — skipping the respawn entirely; a consumed "
    "standby is backfilled asynchronously.  0 disables the tier "
    "(respawn-with-warmup remains the recovery path)",
)
define_flag(
    "FLAGS_cluster_transport",
    "shm",
    "Data-plane transport of the disaggregated serving cluster "
    "(serving/transport.py, docs/SERVING_CLUSTER.md multi-host section): "
    "'shm' rides process-shared ShmRing buffers (single box), 'tcp' rides "
    "length-framed TcpRing sockets with endpoints published through the "
    "TCPStore control tier — the same producer/consumer contract "
    "(TimeoutError is backpressure, never death), so the SIGKILL crash "
    "matrix and bit-exact fail-over hold verbatim on either.  "
    "EngineCluster(transport=...) overrides per cluster",
)
define_flag(
    "FLAGS_cluster_attach_timeout_ms",
    30_000,
    "Shared attach deadline for a cluster worker's boot-time channel "
    "setup (serving/cluster_worker.py): the TCPStore client connect, "
    "both ring attaches (shm attach retry or TcpRing endpoint wait + "
    "dial — serving/transport.py) each ride this budget with "
    "capped-backoff retries, because a worker routinely outraces the "
    "router's bind/publish under load and first-refusal failure would "
    "melt boots into respawn churn",
)
define_flag(
    "FLAGS_pipeline_schedule",
    "1F1B",
    "Default pipeline schedule for PipelineStack/pipeline_llama/"
    "pipeline_gpt built with schedule=None: one of the registered "
    "schedule names (fleet/meta_parallel/schedules.py — FThenB | 1F1B | "
    "ZB-H1).  ZB-H1 runs the zero-bubble split backward: grad-input (B) "
    "on the critical path, grad-weight (W) deferred per the schedule's "
    "tick table.  Changing the flag re-resolves flag-following stacks "
    "and invalidates their cached built steps, the same contract as "
    "FLAGS_decode_chunk (docs/PIPELINE.md)",
)
define_flag(
    "FLAGS_scan_body_guard",
    False,
    "Dev-mode guard: warn when the same lax.scan body function object is "
    "traced under two distinct jit entries — jax's scan-jaxpr cache would "
    "serve the first trace's closed-over tracers to the second "
    "(docs/SCAN_LAYERS.md; _core/dispatch.py)",
)
