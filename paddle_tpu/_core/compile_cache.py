"""Persistent XLA compilation cache + compile telemetry.

Cold starts dominate time-to-first-step for deep models: every process pays
trace + XLA compile for the train/serve step from scratch.  JAX ships a
persistent on-disk compilation cache (the TVM paper's persistent tuning-log
idea applied to whole executables); this module wires it behind
``FLAGS_compilation_cache_dir`` so a warm start deserializes yesterday's
executable instead of recompiling, and taps ``jax.monitoring`` for
trace-time / compile-time / cache-hit counters that
``paddle_tpu.profiler.compile_stats()`` surfaces next to the PR-1 eager
dispatch-cache stats.

Set the flag via env (``FLAGS_compilation_cache_dir=/path``) before import,
or at runtime with ``paddle.set_flags({"FLAGS_compilation_cache_dir":
"/path"})`` — the flags listener applies it immediately.  Pair with
``jit.TrainStep.warmup(sample_batch)`` to pay the (first-run) compile before
traffic.
"""

from __future__ import annotations

import threading

from . import flags

__all__ = ["configure", "compile_stats", "reset_compile_stats"]

_lock = threading.Lock()
_listeners_installed = False
_configured_dir: str | None = None

# populated by jax.monitoring listeners (see _install_listeners)
_stats = {
    "traces": 0,
    "trace_seconds": 0.0,
    "compiles": 0,
    "compile_seconds": 0.0,
    "persistent_cache_hits": 0,
    "persistent_cache_misses": 0,
    "compile_seconds_saved": 0.0,
}

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_SAVED_EVENT = "/jax/compilation_cache/compile_time_saved_sec"


def _on_event(event: str, **kw):
    if event == _HIT_EVENT:
        _stats["persistent_cache_hits"] += 1
    elif event == _MISS_EVENT:
        _stats["persistent_cache_misses"] += 1


def _on_duration(event: str, duration: float, **kw):
    if event == _TRACE_EVENT:
        _stats["traces"] += 1
        _stats["trace_seconds"] += duration
    elif event == _COMPILE_EVENT:
        _stats["compiles"] += 1
        _stats["compile_seconds"] += duration
    elif event == _SAVED_EVENT:
        _stats["compile_seconds_saved"] += duration


def _install_listeners():
    global _listeners_installed
    with _lock:
        if _listeners_installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_listener(_on_event)
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _listeners_installed = True


def configure(cache_dir: str | None = None):
    """Point jax's persistent compilation cache at ``cache_dir`` (default:
    the FLAGS_compilation_cache_dir value; empty disables).  Safe to call
    repeatedly; re-pointing resets jax's in-memory view of the cache."""
    global _configured_dir
    _install_listeners()
    if cache_dir is None:
        cache_dir = str(flags.flag("FLAGS_compilation_cache_dir") or "")
    cache_dir = cache_dir or None
    if cache_dir == _configured_dir:
        return cache_dir
    import jax
    from jax.experimental.compilation_cache import compilation_cache as cc

    try:
        # drop the once-per-task "is the cache in use" decision so a dir set
        # AFTER the first compile still takes effect
        cc.reset_cache()
    except Exception:
        pass
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    if cache_dir is not None:
        jax.config.update("jax_enable_compilation_cache", True)
        # default min-compile-time gate (1s) would skip exactly the small
        # steps CI and CPU smoke runs compile; persist everything
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _configured_dir = cache_dir
    return cache_dir


def compile_stats() -> dict:
    """Trace/compile/persistent-cache counters for this process (monotonic;
    see reset_compile_stats).  `cache_dir` is the active persistent cache
    directory or None."""
    _install_listeners()
    out = dict(_stats)
    out["cache_dir"] = _configured_dir
    return out


def reset_compile_stats():
    for k in _stats:
        _stats[k] = 0 if isinstance(_stats[k], int) else 0.0


@flags.on_change
def _on_flags_change(changed):
    if "FLAGS_compilation_cache_dir" in changed:
        configure()


# Env-var / default wiring at import: a dir set via FLAGS_compilation_cache_dir
# in the environment engages the cache before any compile happens.
if flags.flag("FLAGS_compilation_cache_dir"):
    configure()
else:
    _install_listeners()
