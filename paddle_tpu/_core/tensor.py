"""Tensor facade.

Capability equivalent of the reference's eager Tensor
(paddle/phi/core/dense_tensor.h + pybind methods in
paddle/fluid/pybind/eager_method.cc, math patches in eager_math_op_patch.cc),
built as a thin wrapper over jax.Array:

- the payload is a jax.Array (or a tracer inside jit) — XLA owns memory,
  layout, and streams, so there is no allocator/LoD/stride machinery here;
- autograd metadata (stop_gradient, grad, grad node) lives on the wrapper,
  the tape itself is in `paddle_tpu._core.autograd`;
- Tensor is a registered pytree node so user code written against this API
  can be traced by jax.jit / shard_map unchanged.

Op methods (t.add, t.reshape, ...) are patched onto the class by
`paddle_tpu.tensor` at import time, mirroring the reference's monkey-patch
approach (python/paddle/base/dygraph/math_op_patch.py).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod
from .place import Place, get_default_device

__all__ = ["Tensor", "Parameter", "to_tensor"]


def _as_value(data, dt=None):
    if isinstance(data, Tensor):
        v = data._value
        return v.astype(dtype_mod.to_jax_dtype(dt)) if dt is not None else v
    if isinstance(data, (jax.Array, jnp.ndarray)) and not isinstance(data, np.ndarray):
        return data.astype(dtype_mod.to_jax_dtype(dt)) if dt is not None else data
    arr = np.asarray(data)
    if dt is not None:
        arr = arr.astype(dtype_mod.to_jax_dtype(dt))
    elif arr.dtype == np.float64:
        # Match the reference default of float32 for Python floats.
        arr = arr.astype(np.float32)
    elif arr.dtype == np.int64 and np.isscalar(data):
        arr = arr.astype(np.int64)  # keep int64 for scalars, as paddle does
    return jnp.asarray(arr)


class Tensor:
    """Eager tensor with autograd metadata over a jax.Array payload."""

    __slots__ = (
        "_value",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "_hooks",
        "name",
        "persistable",
        "process_mesh",
        "placements",
        "__weakref__",
    )

    # populated by paddle_tpu.tensor to break import cycles
    _op_module = None

    def __init__(self, value, stop_gradient: bool = True, name: str = ""):
        self._value = value if isinstance(value, (jax.Array,)) or _is_tracer(value) else _as_value(value)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        self._hooks = []
        self.name = name
        self.persistable = False
        self.process_mesh = None  # dist metadata (auto_parallel.shard_tensor)
        self.placements = None

    # ------------------------------------------------------------------ meta
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return dtype_mod.to_paddle_dtype(self._value.dtype)

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self) -> Place:
        return get_default_device()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def numel(self):
        return self.size

    @property
    def T(self):
        return self.transpose(list(range(self.ndim))[::-1])

    @property
    def mT(self):
        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return self.transpose(perm)

    # ------------------------------------------------------------- conversion
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        return self._value.item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self._value)

    def __int__(self):
        return int(self._value)

    def __bool__(self):
        return bool(self._value)

    def __index__(self):
        return int(self._value)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        grad_txt = f", stop_gradient={self.stop_gradient}"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}{grad_txt},\n"
            f"       {np.asarray(jax.device_get(self._value)) if not _is_tracer(self._value) else self._value})"
        )

    # ------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from . import autograd

        autograd.backward_from(self, grad_tensor, retain_graph)

    def register_hook(self, hook):
        self._hooks.append(hook)

        class _Removable:
            def remove(_self):
                try:
                    self._hooks.remove(hook)
                except ValueError:
                    pass

        return _Removable()

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from . import autograd

        return autograd.apply("clone", lambda v: v + jnp.zeros((), v.dtype), self)

    def is_dist(self) -> bool:
        """True if this tensor carries dist metadata (reference
        Tensor.is_dist() for DistTensor)."""
        return self.process_mesh is not None

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None and hasattr(self.grad, "_value"):
            self.grad = Tensor(jnp.zeros_like(self.grad._value))
        else:
            # None, or a SelectedRows sparse grad (no dense buffer to zero)
            self.grad = None

    @property
    def requires_grad(self):
        return not self.stop_gradient

    @requires_grad.setter
    def requires_grad(self, value):
        self.stop_gradient = not value

    # --------------------------------------------------------------- device
    def to(self, *args, **kwargs):
        """to(place)/to(dtype)/to(place, dtype) — dtype converts via cast,
        device moves via jax.device_put.  Unknown strings (typo'd dtypes)
        raise instead of silently no-op'ing (round-1 weak #10)."""
        target_dtype = None
        target_device = None  # Place | device string
        known_devices = ("cpu", "gpu", "tpu", "xpu", "npu", "ipu")
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, Tensor):
                # to(other): adopt the other tensor's dtype (paddle overload)
                target_dtype = a.dtype
            elif isinstance(a, Place):
                target_device = a
            elif isinstance(a, np.dtype) or (isinstance(a, type) and issubclass(a, np.generic)):
                target_dtype = dtype_mod.to_paddle_dtype(a)
            elif isinstance(a, (str, dtype_mod.DType)):
                try:
                    target_dtype = dtype_mod.to_paddle_dtype(a)
                    continue
                except ValueError:
                    pass
                dev = str(a).split(":")[0].lower()
                if dev in known_devices:
                    target_device = str(a)
                else:
                    raise ValueError(
                        f"Tensor.to(): {a!r} is neither a known dtype nor a "
                        f"device string (expected one of {known_devices})"
                    )
        out = self
        if target_dtype is not None and target_dtype != self.dtype:
            out = out.astype(target_dtype)
        if target_device is not None:
            import jax as _jax

            try:
                if isinstance(target_device, Place):
                    dev_obj = target_device.jax_device()
                else:
                    name, _, idx = str(target_device).partition(":")
                    # accelerator names (gpu/tpu/...) mean "the accelerator":
                    # the default backend in this framework
                    plat = "cpu" if name.lower() == "cpu" else _jax.default_backend()
                    if name.lower() != "cpu" and plat == "cpu":
                        import warnings

                        warnings.warn(
                            f"Tensor.to({target_device!r}): no accelerator "
                            "backend available; keeping CPU placement",
                            RuntimeWarning,
                            stacklevel=2,
                        )
                    devs = _jax.devices(plat)
                    if idx:
                        if int(idx) >= len(devs):
                            raise IndexError(
                                f"Tensor.to(): device index {idx} out of range "
                                f"({len(devs)} {plat} devices)"
                            )
                        dev_obj = devs[int(idx)]
                    else:
                        dev_obj = devs[0]
                moved = _jax.device_put(out._value, dev_obj)
                if out is self:
                    out = Tensor(moved, stop_gradient=self.stop_gradient)
                else:
                    out._bind(moved)
            except RuntimeError as e:
                # A requested device move that cannot happen must be loud
                # (same silent-fallback class as the round-3 flags/tiles):
                # keep the placement but tell the user.
                import warnings

                warnings.warn(
                    f"Tensor.to({target_device!r}): backend unavailable "
                    f"({e}); keeping current placement",
                    RuntimeWarning,
                    stacklevel=2,
                )
        return out

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    # ------------------------------------------------------------ value ops
    def set_value(self, value):
        """In-place payload replacement (used by optimizers / state loading)."""
        v = _as_value(value)
        if tuple(v.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: tensor {tuple(self._value.shape)} vs value {tuple(v.shape)}"
            )
        self._value = v.astype(self._value.dtype) if not _is_tracer(v) and not _is_tracer(self._value) else v
        return self

    def _bind(self, value):
        """Rebind payload without checks (tracer binding for functionalization)."""
        self._value = value
        return self

    # Indexing delegates to the op layer for tape support.
    def __getitem__(self, idx):
        from paddle_tpu.tensor import manipulation

        return manipulation._getitem(self, idx)

    def __setitem__(self, idx, value):
        from paddle_tpu.tensor import manipulation

        manipulation._setitem_(self, idx, value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)


class Parameter(Tensor):
    """Trainable tensor: stop_gradient defaults to False (reference:
    python/paddle/base/framework.py EagerParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, value, trainable: bool = True, name: str = ""):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.persistable = True

    def initialize(self):
        """Materialize this parameter on the default accelerator.

        Reference: EagerParamBase.initialize() after paddle.LazyGuard.  Under
        LazyGuard params live in host RAM (jax.default_device(cpu)); this
        pushes the value to the accelerator — or, if the param was given a
        sharding via shard_tensor first, to its sharded placement.
        """
        v = self._value
        if hasattr(v, "sharding") and getattr(v, "_committed", False):
            return self  # already placed deliberately
        self._bind(jax.device_put(v, jax.devices()[0]))
        return self


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor equivalent (reference python/paddle/tensor/creation.py)."""
    return Tensor(_as_value(data, dtype), stop_gradient=stop_gradient)


# ------------------------------------------------------------------ pytree
def _flatten(t: Tensor):
    return (t._value,), (t.stop_gradient, t.name)


def _unflatten(aux, children):
    t = Tensor.__new__(Tensor)
    t._value = children[0]
    t.stop_gradient, t.name = aux
    t.grad = None
    t._grad_node = None
    t._out_index = 0
    t._hooks = []
    t.persistable = False
    t.process_mesh = None
    t.placements = None
    return t


jax.tree_util.register_pytree_node(Tensor, _flatten, _unflatten)


def _flatten_param(p: Parameter):
    return (p._value,), (p.stop_gradient, p.name)


def _unflatten_param(aux, children):
    p = Parameter.__new__(Parameter)
    p._value = children[0]
    p.stop_gradient, p.name = aux
    p.grad = None
    p._grad_node = None
    p._out_index = 0
    p._hooks = []
    p.process_mesh = None
    p.placements = None
    p.trainable = not p.stop_gradient
    p.optimize_attr = {"learning_rate": 1.0}
    p.regularizer = None
    p.need_clip = True
    p.is_distributed = False
    p.persistable = True
    return p


jax.tree_util.register_pytree_node(Parameter, _flatten_param, _unflatten_param)
