"""Device placement.

Capability parity with the reference's Place hierarchy
(paddle/phi/common/place.h:31, python/paddle/device/__init__.py:265) mapped
onto jax.Device.  On TPU there are no manual streams — XLA schedules — so a
Place is just (device_kind, index) resolving to a jax.Device.
"""

from __future__ import annotations

import threading

import jax

__all__ = [
    "Place",
    "TPUPlace",
    "CPUPlace",
    "CUDAPlace",
    "CUDAPinnedPlace",
    "CustomPlace",
    "set_device",
    "get_device",
    "get_default_device",
    "is_compiled_with_tpu",
    "device_count",
]


class Place:
    """Base place: a logical device slot."""

    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self) -> jax.Device:
        devs = _devices_for(self.device_type)
        if not devs:
            raise RuntimeError(f"No devices of type {self.device_type!r} available")
        return devs[self.device_id % len(devs)]

    def is_tpu_place(self):
        return self.device_type == "tpu"

    def is_cpu_place(self):
        return self.device_type == "cpu"

    # GPU never exists in this framework; kept for API-shape compatibility.
    def is_gpu_place(self):
        return False


class TPUPlace(Place):
    device_type = "tpu"


class CPUPlace(Place):
    device_type = "cpu"


class CustomPlace(Place):
    """Any other PJRT backend (pluggable-device analog of the reference's
    CustomPlace, paddle/phi/common/place.h)."""

    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


class CUDAPlace(Place):
    """API-compat alias: reference code written against paddle.CUDAPlace(i)
    (paddle/phi/common/place.h GPUPlace) runs unchanged — the i-th
    accelerator here is the i-th device of the default (TPU) backend."""

    device_type = "accel"

    def jax_device(self) -> jax.Device:
        devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CUDAPinnedPlace(CPUPlace):
    """API-compat alias: pinned host memory is a CUDA-transfer concept; on
    TPU/PJRT host staging is managed by the runtime, so this is CPUPlace."""


def _accel_type() -> str:
    plat = jax.default_backend()
    # 'axon' is the tunneled TPU platform in this environment.
    if plat in ("tpu", "axon"):
        return "tpu"
    return plat


def _devices_for(device_type: str):
    if device_type == "tpu":
        for plat in ("tpu", "axon"):
            try:
                return jax.devices(plat)
            except RuntimeError:
                continue
        return []
    try:
        return jax.devices(device_type)
    except RuntimeError:
        return []


_state = threading.local()


def _parse(device: str) -> Place:
    device = device.lower()
    if ":" in device:
        kind, _, idx = device.partition(":")
        idx = int(idx)
    else:
        kind, idx = device, 0
    kind = {"gpu": "tpu", "xpu": "tpu", "cuda": "tpu"}.get(kind, kind)
    if kind == "cpu":
        return CPUPlace(idx)
    if kind == "tpu":
        return TPUPlace(idx)
    return CustomPlace(kind, idx)


def set_device(device) -> Place:
    """paddle.set_device equivalent (reference python/paddle/device/__init__.py:265)."""
    place = device if isinstance(device, Place) else _parse(str(device))
    _state.place = place
    return place


def get_default_device() -> Place:
    place = getattr(_state, "place", None)
    if place is None:
        accel = _accel_type()
        place = CPUPlace(0) if accel == "cpu" else (
            TPUPlace(0) if accel == "tpu" else CustomPlace(accel, 0)
        )
        _state.place = place
    return place


def get_device() -> str:
    p = get_default_device()
    return f"{p.device_type}:{p.device_id}"


def is_compiled_with_tpu() -> bool:
    return len(_devices_for("tpu")) > 0


def device_count(device_type: str | None = None) -> int:
    if device_type is None:
        device_type = get_default_device().device_type
    return len(_devices_for(device_type))
