"""Global RNG state.

The reference keeps stateful per-device generators (python/paddle/framework/random.py,
CUDA Philox states).  On TPU/XLA randomness must be functional: every random op
consumes a jax PRNG key.  This module provides paddle-style stateful semantics
in eager mode (a global seed + call counter) while staying jit-compatible: a
traced training step installs an explicit key via `key_scope`, and all random
ops inside the trace fold the call counter into that traced key — so randomness
varies per step through a threaded key rather than a baked constant.
"""

from __future__ import annotations

import contextlib
import threading

import jax

__all__ = ["seed", "get_rng_state", "set_rng_state", "next_key", "key_scope", "split_key"]


class _RNG(threading.local):
    def __init__(self):
        self.seed = 0
        self.counter = 0
        self.trace_key = None  # explicit key installed by key_scope


_rng = _RNG()


def seed(s: int):
    """paddle.seed equivalent: reset the global generator."""
    _rng.seed = int(s)
    _rng.counter = 0
    return _rng


def get_rng_state():
    return (_rng.seed, _rng.counter)


def set_rng_state(state):
    _rng.seed, _rng.counter = int(state[0]), int(state[1])


def next_key():
    """Return a fresh PRNG key; advances the global counter.

    Inside `key_scope(step_key)` (used by jitted training steps) the returned
    key derives from the scoped key, so it is a proper traced value.
    """
    from . import dispatch

    if dispatch.in_cached_trace() and not isinstance(_rng.trace_key, jax.core.Tracer):
        # A cached jit would freeze the key AND the counter offset into the
        # compiled op — abort the trace BEFORE consuming a counter tick; the
        # dispatch cache marks the op eager-only and re-runs it eagerly, so
        # the random stream matches cache-off exactly.  This covers both
        # the global-seed path and an eagerly-installed CONCRETE key_scope
        # (a concrete scoped key would bake just the same).  A TRACER scoped
        # key is safe to cache through: the key is a dynamic input of the
        # trace (LayerStack threads a fresh key per call and key_scopes a
        # split of it inside its scan body), and the counter offsets folded
        # into it are the deterministic per-op sequence key_scope defines.
        dispatch.trace_escape("stateful next_key() inside a cached op trace")
    c = _rng.counter
    _rng.counter += 1
    if _rng.trace_key is not None:
        return jax.random.fold_in(_rng.trace_key, c)
    base = jax.random.key(_rng.seed)
    return jax.random.fold_in(base, c)


def split_key(n: int):
    return jax.random.split(next_key(), n)


@contextlib.contextmanager
def key_scope(key):
    """Install an explicit PRNG key (typically a tracer inside jit).

    Counter restarts at 0 within the scope so a given op sequence folds
    deterministic per-call offsets into the per-step key.
    """
    prev_key, prev_counter = _rng.trace_key, _rng.counter
    _rng.trace_key, _rng.counter = key, 0
    try:
        yield
    finally:
        _rng.trace_key, _rng.counter = prev_key, prev_counter
