"""Signature-cached eager dispatch fast path (FLAGS_eager_op_jit).

Every eager op funnels through ``autograd._apply_impl``.  Without this module
the grad path re-traces the op with jax.vjp on every call and the no-grad
path re-dispatches primitive by primitive — the per-op Python tracing cost
the reference avoids with its generated C++ hot path.  Here each signature

    (op name, fn identity, static args/kwargs, input shape/dtype avals,
     diff-mask, needs_grad)

maps to an LRU cache entry holding jitted callables:

- **no-grad**: ``jax.jit`` of the op body — repeated calls skip Python
  tracing and run one compiled XLA computation;
- **grad**: a jitted ``jax.vjp`` pair.  The pullback jax.vjp returns is a
  ``jax.tree_util.Partial`` (a pytree of residual arrays over a static
  function), so the jitted forward can return it and a shared jitted
  backward can apply it — neither retraces after the first call.

Fn identity is NOT ``id(fn)``: op wrappers build a fresh lambda per call
(``lambda v: jnp.clip(v, lo, hi)``), and a recycled id must never serve
another op's compiled trace.  Instead a Python function is keyed by its code
object (one per call site, held strongly so its id is stable) plus a
by-value fingerprint of its closure cells and defaults; C callables are
keyed by identity with a strong reference pinned in the key.

Transparency rules (cache on must be observationally identical to cache
off):

- tracer inputs (inside jax.jit / vmap / grad tracing) bypass;
- closures over arrays/Tensors/tracers/arbitrary objects bypass — this
  automatically excludes RNG-key captures (dropout) and the create_graph
  rebuild closures of ``_vjp_through_tape``;
- stateful RNG consumption (``random.next_key`` without a key_scope) inside
  a cached trace aborts the trace and permanently bypasses the entry, so
  randomness can never be frozen into a compiled call;
- the miss call runs the op EAGERLY and records output dtypes; the first
  hit verifies the jitted result against them, else the entry falls back to
  eager forever;
- any jit failure (data-dependent output shapes, numpy calls on tracers)
  marks the entry eager-only and re-runs eagerly, so user-visible errors
  stay the eager ones;
- ``set_flags()`` clears the cache (op bodies may read flags at trace
  time) and re-applies FLAGS_eager_op_cache_size.

Counters (hits / misses / traces / evictions / bypasses) surface through
``paddle_tpu.profiler.dispatch_cache_stats()``.
"""

from __future__ import annotations

import functools
import threading
import warnings
import weakref
from collections import OrderedDict

import numpy as np
import jax
import jax.numpy as jnp

from . import flags
from . import dtype as dtype_mod
from .tensor import Tensor

__all__ = ["cache", "lookup", "DispatchCache", "FALLBACK", "in_cached_trace",
           "ScanBodyReuseWarning"]

# Sentinel: "run the eager slow path instead" (None is not used — an op fn
# could in principle return None).
FALLBACK = object()

# dtype -> is-inexact memo (jnp.issubdtype is ~10us; this path runs per op
# call per tensor arg)
_INEXACT: dict = {}


def _is_inexact(dt) -> bool:
    r = _INEXACT.get(dt)
    if r is None:
        r = _INEXACT[dt] = bool(jnp.issubdtype(dt, jnp.inexact))
    return r


class _Uncacheable(Exception):
    """The call signature contains something we refuse to key on."""


class _TraceEscape(Exception):
    """Raised (via random.next_key) when a cached trace touches host-side
    mutable state that must advance per call."""


class _TraceGuard(threading.local):
    def __init__(self):
        self.active = False


_trace_guard = _TraceGuard()


def in_cached_trace() -> bool:
    """True while jax is tracing an op body for this cache (consulted by
    _core.random.next_key: stateful RNG must abort the trace)."""
    return _trace_guard.active


def trace_escape(reason: str):
    """Abort the in-flight cached trace; the caller falls back to eager."""
    raise _TraceEscape(reason)


# --------------------------------------------------------- key normalization

_SIMPLE = (type(None), bool, int, float, complex, str, bytes)


def _norm(v, depth=0):
    """Normalize a static value into a hashable key component.

    Equal-by-value statics must produce equal components (fresh lambdas per
    call close over new-but-equal values).  Identity-keyed components embed
    the object itself in the key so the LRU pins it alive and its id cannot
    be recycled into a colliding entry.
    """
    if isinstance(v, jax.core.Tracer) or isinstance(v, (Tensor, jax.Array, np.ndarray)):
        raise _Uncacheable
    if isinstance(v, _SIMPLE):
        return (type(v).__name__, v)
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return ("np", str(v.dtype), v.item())
    if depth > 5:
        raise _Uncacheable
    if isinstance(v, (tuple, list)):
        return ("seq", isinstance(v, tuple), tuple(_norm(x, depth + 1) for x in v))
    if isinstance(v, dict):
        try:
            items = sorted(v.items())
        except TypeError as e:
            raise _Uncacheable from e
        return ("dict", tuple((k, _norm(x, depth + 1)) for k, x in items))
    if isinstance(v, (set, frozenset)):
        return ("set", frozenset(_norm(x, depth + 1) for x in v))
    if isinstance(v, slice):
        return ("slice", _norm(v.start, depth + 1), _norm(v.stop, depth + 1),
                _norm(v.step, depth + 1))
    if isinstance(v, np.dtype):
        return ("npdtype", str(v))
    if isinstance(v, dtype_mod.DType):
        return ("pdtype", str(v))
    if isinstance(v, functools.partial):
        return ("partial", _norm(v.func, depth + 1), _norm(tuple(v.args), depth + 1),
                _norm(v.keywords or {}, depth + 1))
    if isinstance(v, type) or callable(v):
        return ("id", id(v), v)
    raise _Uncacheable


class _WeakIdRef:
    """Identity-keyed cache component holding its object WEAKLY: equal only
    when both referents are alive and the same object (never referent
    __eq__, which is elementwise for Tensor-likes).  A recycled id pairs
    with a DEAD ref that equals nothing — the stale entry just misses and
    ages out of the LRU instead of colliding or pinning the object."""

    __slots__ = ("ref", "_id")

    def __init__(self, obj):
        import weakref as _weakref

        self.ref = _weakref.ref(obj)
        self._id = id(obj)

    def __hash__(self):
        return self._id

    def __eq__(self, other):
        if not isinstance(other, _WeakIdRef):
            return NotImplemented
        a = self.ref()
        return a is not None and a is other.ref()


def _fn_key(fn):
    code = getattr(fn, "__code__", None)
    if code is None:
        # C function / builtin / jnp ufunc object: stable module-level
        # singletons — identity with a pinned reference.
        return ("cfn", id(fn), fn)
    parts = [("code", id(code), code)]
    self_obj = getattr(fn, "__self__", None)
    if self_obj is not None:
        # bound method: the instance is part of identity, but held WEAKLY —
        # pinning it would keep e.g. a LayerStack's stacked weights alive in
        # the LRU after the model is dropped (see _WeakIdRef for why id
        # recycling cannot collide).
        try:
            parts.append(("self", id(self_obj), _WeakIdRef(self_obj)))
        except TypeError:  # not weakref-able: pin strongly like before
            parts.append(("self", id(self_obj), self_obj))
    if getattr(fn, "__defaults__", None):
        parts.append(_norm(fn.__defaults__))
    if getattr(fn, "__kwdefaults__", None):
        parts.append(_norm(fn.__kwdefaults__))
    closure = getattr(fn, "__closure__", None)
    if closure:
        try:
            parts.append(tuple(_norm(c.cell_contents) for c in closure))
        except ValueError as e:  # empty cell
            raise _Uncacheable from e
    return ("fn", tuple(parts))


# ------------------------------------------------------------------- entries


# Hits served eagerly before a signature is considered hot enough to pay a
# compile: after the miss, _HOT_CALLS repeats run eager, so the compile
# lands on call _HOT_CALLS+2 (the 4th) of a signature.  Test-style
# workloads touching a signature a few times never compile (a compile
# would be pure loss there); loops cross the ramp immediately.
_HOT_CALLS = 2


class _Entry:
    """Per-signature state.  Deliberately does NOT pin the recording call's
    fn/args: the jit is built from the fn of the call that crosses the
    hotness ramp — that fn's closure provably equals the key by value (the
    key was just built from it), whereas the first call's closure cells may
    have been mutated by the caller since recording."""

    __slots__ = ("out_meta", "ngrad_jit", "fwd_jit", "bwd_jit", "bypass",
                 "verified", "uses")

    def __init__(self):
        self.out_meta = None  # [(dtype, weak_type)] from the eager miss
        self.ngrad_jit = None
        self.fwd_jit = None
        # per-entry (not module-global) so LRU eviction / clear() releases
        # the compiled backward executable along with the forward
        self.bwd_jit = None
        self.bypass = False
        self.verified = False
        self.uses = 0  # hit count while still below _HOT_CALLS


class DispatchCache:
    """LRU over dispatch signatures with hit/miss/trace/eviction counters."""

    def __init__(self, maxsize: int = 1024):
        self._lock = threading.RLock()
        self._entries: OrderedDict = OrderedDict()
        self.maxsize = max(1, int(maxsize))
        self.hits = 0
        self.misses = 0
        self.traces = 0
        self.evictions = 0
        self.bypasses = 0

    def get(self, key):
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                self._entries.move_to_end(key)
            return e

    def put(self, key, entry):
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
                self.evictions += 1

    def clear(self):
        with self._lock:
            self._entries.clear()

    def resize(self, maxsize: int):
        with self._lock:
            self.maxsize = max(1, int(maxsize))
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
                self.evictions += 1

    def reset_stats(self):
        self.hits = self.misses = self.traces = 0
        self.evictions = self.bypasses = 0

    def __len__(self):
        return len(self._entries)

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "traces": self.traces,
            "evictions": self.evictions,
            "bypasses": self.bypasses,
            "size": len(self._entries),
            "capacity": self.maxsize,
            "enabled": bool(flags.flag("FLAGS_eager_op_jit")),
        }


cache = DispatchCache(int(flags.flag("FLAGS_eager_op_cache_size")))


@flags.on_change
def _on_flags_change(_changed):
    # Any flag may be read inside an op body at trace time
    # (FLAGS_tpu_matmul_precision, FLAGS_default_dtype, ...): drop every
    # cached trace rather than track per-flag dependencies.
    cache.resize(int(flags.flag("FLAGS_eager_op_cache_size")))
    cache.clear()
    if flags.flag("FLAGS_scan_body_guard"):
        _install_scan_guard()


# ------------------------------------------------- scan-body identity guard
#
# jax's lax.scan caches the traced body jaxpr keyed by the body FUNCTION'S
# IDENTITY (+ avals).  A body function object shared across two distinct
# jit traces hands the second trace the FIRST trace's cached jaxpr, whose
# consts are that trace's closed-over tracers (bound model weights) →
# UnexpectedTracerError, or silently stale constants.  PR 3 hit exactly
# this in the macro-step decode path; the fix is structural (define scan
# bodies INSIDE the traced function — docs/SCAN_LAYERS.md), and this
# dev-mode guard (FLAGS_scan_body_guard) makes regressions loud: it wraps
# jax.lax.scan and warns when the same body object is traced under two
# distinct jit entries.


class ScanBodyReuseWarning(UserWarning):
    """Same lax.scan body function object traced under two jit entries."""


_orig_lax_scan = None
# id(body fn) -> (weakref(body) | None, weakref(trace) | None, label);
# a collected body removes its own entry, so a recycled id cannot collide.
_scan_seen: dict = {}


def _current_jit_trace():
    """The innermost DynamicJaxprTrace when jax is jit-tracing, else None
    (the hazard needs closed-over consts to be tracers of an enclosing
    trace; eager scans are safe)."""
    try:
        from jax._src import core as _src_core

        t = _src_core.trace_ctx.trace
    except Exception:
        return None
    return t if type(t).__name__ == "DynamicJaxprTrace" else None


def _guarded_scan(f, *args, **kwargs):
    if flags.flag("FLAGS_scan_body_guard") and callable(f):
        trace = _current_jit_trace()
        if trace is not None:
            key = id(f)
            rec = _scan_seen.get(key)
            if rec is not None and rec[0]() is not None:
                prev = rec[1]() if rec[1] is not None else None
                if prev is not trace:
                    # previous trace is a different live trace, or already
                    # dead — either way jax's scan-jaxpr cache may serve
                    # that trace's consts to this one
                    warnings.warn(
                        f"lax.scan body {rec[2]} is shared across two "
                        "distinct jit traces: jax caches the scan jaxpr by "
                        "body-function identity, so the second trace can "
                        "receive the first trace's closed-over tracer "
                        "consts (UnexpectedTracerError / stale constants). "
                        "Define the scan body inside the jit-traced "
                        "function so each trace gets a fresh body object "
                        "(docs/SCAN_LAYERS.md).",
                        ScanBodyReuseWarning, stacklevel=2)
            label = getattr(f, "__qualname__", None) or repr(f)
            try:
                fref = weakref.ref(f, lambda _r, _k=key: _scan_seen.pop(_k, None))
            except TypeError:
                # not weakref-able (e.g. a __slots__ callable): pin it so
                # id(f) can never be recycled onto a different body while
                # the record exists — the entry leaks, but only under this
                # dev-mode flag and only for such bodies
                fref = (lambda _f=f: _f)
            try:
                tref = weakref.ref(trace)
            except TypeError:
                tref = None
            _scan_seen[key] = (fref, tref, label)
    return _orig_lax_scan(f, *args, **kwargs)


def _install_scan_guard():
    """Idempotently wrap the public jax.lax.scan alias (the wrapper is a
    no-op passthrough while the flag is off, so it is never uninstalled)."""
    global _orig_lax_scan
    if _orig_lax_scan is not None:
        return
    import jax.lax as _lax

    _orig_lax_scan = _lax.scan
    _lax.scan = functools.wraps(_orig_lax_scan)(_guarded_scan)


if flags.flag("FLAGS_scan_body_guard"):  # env-enabled at import
    _install_scan_guard()


# ------------------------------------------------------------ jit factories


def _weak_fn(fn):
    """Return a zero-arg getter for `fn` that does not pin a bound method's
    receiver: the entry's key already only weak-holds the receiver
    (_WeakIdRef), so the stored jit closure must not re-pin it — else a
    dropped LayerStack's stacked weights live on inside the LRU.  A dead
    receiver is unreachable through lookup (its key never matches), so the
    getter can only fire while the receiver is alive."""
    self_obj = getattr(fn, "__self__", None)
    if self_obj is None:
        return lambda: fn
    import weakref

    func, ref = fn.__func__, weakref.ref(self_obj)

    def get():
        obj = ref()
        if obj is None:  # unreachable via cache lookup; defensive only
            raise ReferenceError("dispatch-cache receiver was collected")
        return func.__get__(obj)

    return get


def _make_nograd_jit(handle):
    get_fn, kwargs = _weak_fn(handle.fn), dict(handle.kwargs)
    statics, dyn_pos = handle.statics, handle.dyn_pos

    def run(dyn_vals):
        # Body executes only while jax traces (then the compiled call is
        # served from jax's own cache) — the counter counts real traces.
        # Guard save/restore (not =False): a nested cached dispatch inside
        # an outer cached trace must not clear the outer trace's guard —
        # that would let a later next_key() in the outer body skip its
        # freeze-escape and bake a concrete key into the compiled op.
        cache.traces += 1
        prev, _trace_guard.active = _trace_guard.active, True
        try:
            full = list(statics)
            for p, v in zip(dyn_pos, dyn_vals):
                full[p] = v
            return get_fn()(*full, **kwargs)
        finally:
            _trace_guard.active = prev

    return jax.jit(run)


def _prefers_eager(handle, dyn_vals) -> bool:
    """Trace the op once and count primitives: a 1-2 primitive body gains
    nothing from a cached jit on the no-grad path (eager jax already serves
    each primitive from its C++ cache; the Python jit-call overhead would
    dominate), so such entries run eager.  Composites — where one fused
    compiled call replaces N dispatches — keep the jit.  Grad-path entries
    never come through here: uncached vjp pays a full retrace per call, so
    caching always wins there."""
    fn, kwargs = handle.fn, dict(handle.kwargs)
    statics, dyn_pos = handle.statics, handle.dyn_pos

    def run(dyn):
        full = list(statics)
        for p, v in zip(dyn_pos, dyn):
            full[p] = v
        return fn(*full, **kwargs)

    cache.traces += 1
    prev, _trace_guard.active = _trace_guard.active, True
    try:
        jaxpr = jax.make_jaxpr(run)(tuple(dyn_vals))
    finally:
        _trace_guard.active = prev
    return len(jaxpr.jaxpr.eqns) <= 2


def _make_fwd_jit(handle):
    get_fn, kwargs = _weak_fn(handle.fn), dict(handle.kwargs)
    statics, diff_pos = handle.statics, handle.diff_pos
    diff_set = set(diff_pos)
    nondiff_pos = [p for p in handle.dyn_pos if p not in diff_set]

    def fwd(diff_vals, nondiff_vals):
        cache.traces += 1
        prev, _trace_guard.active = _trace_guard.active, True
        try:
            fn = get_fn()
            base = list(statics)
            for p, v in zip(nondiff_pos, nondiff_vals):
                base[p] = v

            def g(*dv):
                full = list(base)
                for p, v in zip(diff_pos, dv):
                    full[p] = v
                return fn(*full, **kwargs)

            # The pullback is a tree_util.Partial: residual arrays over a
            # static function — a legal jit output.
            return jax.vjp(g, *diff_vals)
        finally:
            _trace_guard.active = prev

    return jax.jit(fwd)


def _bwd(vjp_partial, cot):
    cache.traces += 1
    return vjp_partial(cot)


class _CachedVjp:
    """GradNode.vjp_fn for cached nodes: applies the residual-carrying
    Partial through the entry's jitted backward (compiled once per op
    trace, since every hit of one entry returns Partials with the same
    treedef)."""

    __slots__ = ("partial", "bwd_jit")

    def __init__(self, partial, bwd_jit):
        self.partial = partial
        self.bwd_jit = bwd_jit

    def __call__(self, cot):
        try:
            return self.bwd_jit(self.partial, cot)
        except Exception:
            # Transparency: whatever the jitted application rejects, the
            # plain pullback still handles.
            return self.partial(cot)


def _verify(entry, out) -> bool:
    """First-hit check that the jitted result matches the eager miss call's
    output leaf dtypes (guards weak-type / scalar-promotion drift)."""
    leaves = jax.tree_util.tree_leaves(out)
    meta = entry.out_meta
    if meta is None or len(leaves) != len(meta):
        return False
    for v, (dt, weak) in zip(leaves, meta):
        if (not isinstance(v, jax.Array) or v.dtype != dt
                or bool(getattr(v, "weak_type", False)) != weak):
            return False
    entry.verified = True
    return True


# ------------------------------------------------------------------- lookup


class _Handle:
    """One dispatch attempt: the built key plus the split arg values."""

    __slots__ = ("key", "entry", "hit", "fn", "kwargs", "statics", "dyn_pos",
                 "diff_pos", "dyn_vals")

    def call_nograd(self):
        e = self.entry
        if e.ngrad_jit is None and e.uses < _HOT_CALLS:
            # hotness ramp: served eager — reclassify the lookup's hit
            # (locked: e.uses and the hit/bypass swap are read-modify-write)
            with cache._lock:
                e.uses += 1
                cache.hits -= 1
                cache.bypasses += 1
            return FALLBACK
        try:
            if e.ngrad_jit is None:
                # primitive-count probe OUTSIDE the lock: it traces the op
                # body (seconds for a big composite), and cache._lock is the
                # global lock every lookup takes — holding it would stall
                # all other threads' dispatch.  A racing duplicate probe is
                # harmless (deterministic outcome, jax dedupes compiles).
                prefers = _prefers_eager(self, self.dyn_vals)
                with cache._lock:
                    # under the lock so concurrent threads share one jit
                    # wrapper (jax then dedupes the compile)
                    if e.ngrad_jit is None and not e.bypass:
                        if prefers:
                            e.bypass = True
                        else:
                            e.ngrad_jit = _make_nograd_jit(self)
                if e.ngrad_jit is None:  # bypassed (by us or a peer)
                    cache.bypasses += 1
                    return FALLBACK
            out = e.ngrad_jit(tuple(self.dyn_vals))
        except Exception:
            e.bypass = True
            cache.bypasses += 1
            return FALLBACK
        if not e.verified and not _verify(e, out):
            e.bypass = True
            cache.bypasses += 1
            return FALLBACK
        return out

    def call_grad(self, diff_idx):
        e = self.entry
        if diff_idx != self.diff_pos:  # partition drift: never serve a stale trace
            e.bypass = True
            cache.bypasses += 1
            return FALLBACK
        if e.fwd_jit is None and e.uses < _HOT_CALLS:
            # hotness ramp: served eager — reclassify the lookup's hit
            # (locked: e.uses and the hit/bypass swap are read-modify-write)
            with cache._lock:
                e.uses += 1
                cache.hits -= 1
                cache.bypasses += 1
            return FALLBACK
        diff_set = set(self.diff_pos)
        diff_vals, nondiff_vals = [], []
        for p, v in zip(self.dyn_pos, self.dyn_vals):
            (diff_vals if p in diff_set else nondiff_vals).append(v)
        try:
            if e.fwd_jit is None:
                with cache._lock:
                    if e.fwd_jit is None:
                        e.bwd_jit = jax.jit(_bwd)
                        e.fwd_jit = _make_fwd_jit(self)
            out, partial = e.fwd_jit(tuple(diff_vals), tuple(nondiff_vals))
        except Exception:
            e.bypass = True
            cache.bypasses += 1
            return FALLBACK
        if not e.verified and not _verify(e, out):
            e.bypass = True
            cache.bypasses += 1
            return FALLBACK
        return out, _CachedVjp(partial, e.bwd_jit)

    def record(self, out):
        """After the eager miss run: store the entry (jits build lazily,
        from the fn of the call that crosses the hotness ramp).  Non-array
        output leaves mark the op eager-only."""
        entry = _Entry()
        meta = []
        for v in jax.tree_util.tree_leaves(out):
            if isinstance(v, jax.core.Tracer) or not isinstance(v, jax.Array):
                entry.bypass = True
                break
            meta.append((v.dtype, bool(getattr(v, "weak_type", False))))
        else:
            entry.out_meta = meta
        cache.put(self.key, entry)


def lookup(name, fn, args, kwargs, needs_grad):
    """Build the signature for this call; return a _Handle, or None when the
    call must take the eager slow path (uncacheable / tracers / bypassed)."""
    try:
        arg_key, statics, dyn_pos, dyn_vals, diff_pos = [], [], [], [], []
        for i, a in enumerate(args):
            if isinstance(a, Tensor):
                v = a._value
                if isinstance(v, jax.core.Tracer) or not isinstance(v, jax.Array):
                    cache.bypasses += 1
                    return None
                diff = (needs_grad and not a.stop_gradient
                        and _is_inexact(v.dtype))
                arg_key.append(("T", v.shape, v.dtype,
                                bool(getattr(v, "weak_type", False)), diff))
                statics.append(None)
                dyn_pos.append(i)
                dyn_vals.append(v)
                if diff:
                    diff_pos.append(i)
            elif isinstance(a, jax.core.Tracer):
                cache.bypasses += 1
                return None
            elif isinstance(a, jax.Array):
                arg_key.append(("A", a.shape, a.dtype,
                                bool(getattr(a, "weak_type", False))))
                statics.append(None)
                dyn_pos.append(i)
                dyn_vals.append(a)
            elif isinstance(a, np.ndarray):
                # numpy positional args keep numpy semantics inside fn; a
                # traced call would hand fn a tracer instead — stay eager.
                cache.bypasses += 1
                return None
            else:
                arg_key.append(("S", _norm(a)))
                # shallow-copy containers so a caller mutating its arg after
                # the call cannot skew the baked statics
                statics.append(list(a) if isinstance(a, list)
                               else dict(a) if isinstance(a, dict) else a)
        key = (name, bool(needs_grad), _fn_key(fn), tuple(arg_key),
               _norm(kwargs) if kwargs else None)
        entry = cache.get(key)  # in the try: an unhashable __hash__ bypasses
    except (_Uncacheable, TypeError, ValueError):
        cache.bypasses += 1
        return None

    h = _Handle()
    h.key = key
    h.fn = fn
    h.kwargs = kwargs
    h.statics = statics
    h.dyn_pos = dyn_pos
    h.diff_pos = diff_pos
    h.dyn_vals = dyn_vals

    if entry is None:
        cache.misses += 1
        h.entry, h.hit = None, False
    elif entry.bypass:
        cache.bypasses += 1
        return None
    else:
        cache.hits += 1
        h.entry, h.hit = entry, True
    return h
