"""paddle.pir surface (reference: python/paddle/pir/ over the C++ PIR
dialect).

TPU-native: there is ONE Program abstraction (static/program.py) playing
the roles of both the legacy ProgramDesc and PIR (SURVEY §7's folding);
this module exposes it under the pir names so reference code addressing
`paddle.pir` resolves.  Translation helpers are identity: every captured
program already IS the "new IR" here.
"""

from __future__ import annotations

from paddle_tpu.static.program import (  # noqa: F401
    Block,
    Operator,
    Program,
    Variable as Value,  # pir.Value ~ the SSA value handle
)
from paddle_tpu.static.program import in_dynamic_mode  # noqa: F401

__all__ = ["Program", "Block", "Operator", "Value", "core",
           "translate_to_pir", "is_pir_mode"]


class core:  # noqa: N801 — reference exposes pir.core
    """Minimal pir.core namespace."""

    @staticmethod
    def _to_pir(program):
        return program


def translate_to_pir(program):
    """Identity: the one Program IS the new IR (see module docstring)."""
    return program


def is_pir_mode() -> bool:
    """Always true: there is no legacy IR to be in."""
    return True
