"""paddle.quantization equivalent (reference:
python/paddle/quantization/__init__.py — QuantConfig, BaseQuanter,
BaseObserver, quanter factory, QAT, PTQ; observers/abs_max.py,
quanters/abs_max.py).

TPU-first: fake-quantization is a pure jnp round-clip with a
straight-through estimator via jax.custom_vjp, so QAT steps stay fully
jit-compilable; observers accumulate ranges as host-side state between
compiled steps (the same split the reference makes between pass-collected
statistics and kernel compute)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "QuantConfig", "BaseQuanter", "BaseObserver", "quanter", "QAT", "PTQ",
    "AbsMaxObserver", "FakeQuanterWithAbsMaxObserver", "QuantedLinear",
    "QuantedConv2D", "ChannelWiseAbsMaxObserver",
    "FakeQuanterChannelWiseAbsMax", "PercentileObserver",
    "Int8DeployedLinear", "Int8DeployedConv2D",
]


# straight-through fake quant -------------------------------------------------

@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    s = jnp.maximum(scale, 1e-9)
    return jnp.clip(jnp.round(x / s * qmax), -qmax - 1, qmax) * s / qmax


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # straight-through: pass gradient inside the clip range, zero outside
    mask = (jnp.abs(x) <= jnp.maximum(scale, 1e-9)).astype(g.dtype)
    return g * mask, None, None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


class BaseObserver(Layer):
    """Collects tensor statistics to derive scales (reference
    quantization/base_observer.py:22)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


class BaseQuanter(BaseObserver):
    """Trainable/simulated quantizer applied during QAT (reference
    quantization/base_quanter.py:22)."""


class AbsMaxObserver(BaseObserver):
    """Running abs-max observer (reference
    quantization/observers/abs_max.py:30)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._max = 1e-9

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        self._max = max(self._max, float(jnp.max(jnp.abs(xv))))
        return x

    def scales(self):
        return Tensor(jnp.asarray(self._max, jnp.float32))


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT fake-quant with moving-average abs-max (reference
    quantization/quanters/abs_max.py:32)."""

    def __init__(self, moving_rate=0.9, quant_bits=8, dtype="float32", name=None):
        super().__init__(quant_bits)
        self._moving_rate = moving_rate
        self._scale = 1e-9

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if self.training:
            cur = float(jax.lax.stop_gradient(jnp.max(jnp.abs(xv))))
            r = self._moving_rate
            self._scale = r * self._scale + (1 - r) * cur
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        return Tensor(_fake_quant(xv, jnp.asarray(self._scale, xv.dtype), qmax))

    def scales(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))


class ChannelWiseAbsMaxObserver(BaseObserver):
    """Per-output-channel abs-max (reference observers — the weight
    observer for linear/conv: channel-wise scales quantize far tighter
    than one tensor-wide scale)."""

    def __init__(self, quant_bits=8, quant_axis=None):
        super().__init__(quant_bits)
        self._axis = quant_axis
        self._max = None

    def quant_axis(self):
        return self._axis if self._axis is not None else -1

    def _reduce(self, xv):
        # default axis: the OUT channel — last dim for [in, out] linear
        # weights, dim 0 for [out, in, kh, kw] conv weights
        ax = self._axis
        if ax is None:
            ax = 0 if xv.ndim == 4 else xv.ndim - 1
        axes = tuple(i for i in range(xv.ndim) if i != ax)
        return jnp.max(jnp.abs(xv), axis=axes)

    def forward(self, x):
        import numpy as np

        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        cur = np.asarray(self._reduce(xv), np.float32)  # host state
        self._max = cur if self._max is None else np.maximum(self._max, cur)
        return x

    def scales(self):
        return Tensor(jnp.maximum(jnp.asarray(self._max, jnp.float32), 1e-9))


class FakeQuanterChannelWiseAbsMax(BaseQuanter):
    """Channel-wise QAT fake quanter (reference
    quanters/abs_max.py FakeQuanterChannelWiseAbsMaxObserver): per-output
    -channel scale tracked as a running max during training; the fake
    quant broadcasts the channel scales."""

    def __init__(self, quant_bits=8, quant_axis=None, dtype="float32", name=None):
        super().__init__(quant_bits)
        self._axis = quant_axis
        self._scale = None

    def quant_axis(self):
        return self._axis if self._axis is not None else -1

    def _axis_for(self, xv):
        if self._axis is not None:
            return self._axis
        return 0 if xv.ndim == 4 else xv.ndim - 1

    def forward(self, x):
        import numpy as np

        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        ax = self._axis_for(xv)
        axes = tuple(i for i in range(xv.ndim) if i != ax)
        if self.training:
            # host-side running max (the module's eager-observer contract)
            cur = np.asarray(jnp.max(jnp.abs(xv), axis=axes), np.float32)
            self._scale = cur if self._scale is None else np.maximum(self._scale, cur)
        # the per-channel scale stays float32 and the fake-quant round/clip
        # runs in float32: an activation-dtype (bf16) scale quantizes to a
        # DIFFERENT grid than the deployed int8 kernel's f32 scale, so QAT
        # would train against the wrong quantization error
        scale = jnp.maximum(jnp.asarray(
            self._scale if self._scale is not None else np.ones(xv.shape[ax]),
            jnp.float32), 1e-9)
        shape = [1] * xv.ndim
        shape[ax] = xv.shape[ax]
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        out = _fake_quant(xv.astype(jnp.float32), scale.reshape(shape), qmax)
        return Tensor(out.astype(xv.dtype))

    def scales(self):
        return Tensor(jnp.maximum(jnp.asarray(self._scale, jnp.float32), 1e-9))


class PercentileObserver(BaseObserver):
    """Percentile activation observer (reference observers hist/percentile
    family): running EMA of a high quantile of |x| — robust to outlier
    activations that would blow an abs-max scale."""

    def __init__(self, quant_bits=8, percentile=99.9, moving_rate=0.9):
        super().__init__(quant_bits)
        self._p = float(percentile)
        self._r = float(moving_rate)
        self._scale = None

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        cur = float(jnp.percentile(jnp.abs(xv.astype(jnp.float32)), self._p))
        self._scale = cur if self._scale is None else (
            self._r * self._scale + (1 - self._r) * cur)
        return x

    def scales(self):
        return Tensor(jnp.asarray(max(self._scale or 0.0, 1e-9), jnp.float32))


class _QuanterFactory:
    """Partial-binding factory (reference quantization/factory.py:49)."""

    def __init__(self, cls, *args, **kwargs):
        self.cls = cls
        self.args = args
        self.kwargs = kwargs

    def _instance(self, layer=None):
        return self.cls(*self.args, **self.kwargs)


def quanter(cls_or_name, *args, **kwargs):
    """Decorator/factory helper (reference factory.py:76): returns a
    factory whose instances are created per quantified tensor."""
    if isinstance(cls_or_name, type):
        return _QuanterFactory(cls_or_name, *args, **kwargs)

    def wrap(cls):
        return cls

    return wrap


class QuantConfig:
    """Maps layers/types/names to (activation, weight) quanter factories
    (reference quantization/config.py:57)."""

    def __init__(self, activation=None, weight=None):
        self._global_act = activation
        self._global_wt = weight
        self._layer_cfg = {}  # id(layer) -> (act, wt)
        self._type_cfg = {}  # layer type -> (act, wt)
        self._name_cfg = {}  # layer full name -> (act, wt)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) else [layer_name]
        for n in names:
            self._name_cfg[n] = (activation, weight)

    def _lookup(self, layer, name):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        if name in self._name_cfg:
            return self._name_cfg[name]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        if self._global_act or self._global_wt:
            return (self._global_act, self._global_wt)
        return None


class _QuantedWrapper(Layer):
    """Wraps a layer with activation/weight quanters (reference
    quantization/wrapper.py ObserveWrapper + imperative quant layers)."""

    def __init__(self, layer, act_factory, wt_factory):
        super().__init__()
        self._inner = layer
        self.activation_quanter = act_factory._instance(layer) if act_factory else None
        self.weight_quanter = wt_factory._instance(layer) if wt_factory else None

    def forward(self, x, *args, **kwargs):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None and hasattr(self._inner, "weight"):
            orig = self._inner.weight
            q = self.weight_quanter(orig)
            try:
                self._inner.weight = q
                return self._inner(x, *args, **kwargs)
            finally:
                self._inner.weight = orig
        return self._inner(x, *args, **kwargs)


QuantedLinear = _QuantedWrapper
QuantedConv2D = _QuantedWrapper


def _swap_layers(model, config, factory_filter):
    from paddle_tpu import nn

    quantable = (nn.Linear, nn.Conv2D) if hasattr(nn, "Conv2D") else (nn.Linear,)
    for name, sub in list(model._sub_layers.items()):
        cfg = config._lookup(sub, name)
        if cfg is not None and isinstance(sub, quantable):
            act, wt = cfg
            model._sub_layers[name] = _QuantedWrapper(sub, factory_filter(act), factory_filter(wt))
        else:
            _swap_layers(sub, config, factory_filter)
    return model


def _funnel_fake_quant(x, scale, qmax):
    """Frozen-scale fake quant through the op funnel (static-capturable)."""
    from paddle_tpu._core.autograd import apply

    def _fq(v):
        s = jnp.maximum(jnp.asarray(scale, v.dtype), 1e-9)
        return jnp.clip(jnp.round(v / s * qmax), -qmax - 1, qmax) * s / qmax

    return apply("fake_quant", _fq, x)


class Int8DeployedLinear(Layer):
    """Deployed weight-only int8 linear: weight stored AS int8 with a per-
    output-channel scale (the nn.quant weight_only serving contract); the
    optional frozen activation fake-quant reproduces QAT eval numerics.
    jit.save of a converted model bakes the int8 weights into the
    artifact."""

    def __init__(self, q, scale, bias=None, act_scale=None, act_bits=8,
                 wt_bits=8):
        super().__init__()
        self.register_buffer("weight_int8", Tensor(jnp.asarray(q, jnp.int8)))
        self.register_buffer("weight_scale",
                             Tensor(jnp.asarray(scale, jnp.float32)))
        self._bias = bias
        self._act_scale = None if act_scale is None else float(act_scale)
        self._act_qmax = float(2 ** (act_bits - 1) - 1)
        self._wt_qmax = float(2 ** (wt_bits - 1) - 1)

    def forward(self, x):
        import paddle_tpu as paddle

        if self._act_scale is not None:
            x = _funnel_fake_quant(x, self._act_scale, self._act_qmax)
        w = self.weight_int8.astype("float32") * (
            self.weight_scale / self._wt_qmax)
        out = paddle.matmul(x, w)
        if self._bias is not None:
            out = out + self._bias
        return out


class Int8DeployedConv2D(Layer):
    """Deployed weight-only int8 conv2d (per-out-channel scales)."""

    def __init__(self, q, scale, bias, conv_cfg, act_scale=None, act_bits=8,
                 wt_bits=8):
        super().__init__()
        self.register_buffer("weight_int8", Tensor(jnp.asarray(q, jnp.int8)))
        self.register_buffer("weight_scale",
                             Tensor(jnp.asarray(scale, jnp.float32)))
        self._bias = bias
        self._cfg = dict(conv_cfg)  # stride/padding/dilation/groups
        self._act_scale = None if act_scale is None else float(act_scale)
        self._act_qmax = float(2 ** (act_bits - 1) - 1)
        self._wt_qmax = float(2 ** (wt_bits - 1) - 1)

    def forward(self, x):
        import paddle_tpu.nn.functional as F

        if self._act_scale is not None:
            x = _funnel_fake_quant(x, self._act_scale, self._act_qmax)
        scale = self.weight_scale / self._wt_qmax
        w = self.weight_int8.astype("float32") * scale.reshape([-1, 1, 1, 1])
        return F.conv2d(x, w, bias=self._bias, **self._cfg)


def _lower_wrapper(wrapper):
    """_QuantedWrapper -> deployed int8 layer using the TRAINED observer
    scales (not recomputed from the weights: QAT learned them)."""
    import numpy as np

    from paddle_tpu import nn

    inner = wrapper._inner
    wq = wrapper.weight_quanter
    aq = wrapper.activation_quanter
    if wq is None or not hasattr(inner, "weight"):
        return None
    bits = wq.bit_length()
    qmax = float(2 ** (bits - 1) - 1)
    w = np.asarray(inner.weight._value, np.float32)
    scales = np.asarray(wq.scales()._value, np.float32)
    act_scale = None
    if aq is not None:
        act_scale = float(np.asarray(aq.scales()._value))

    if isinstance(inner, nn.Linear):
        ax = w.ndim - 1
        if scales.ndim == 0:  # tensor-wide quanter
            scales = np.full((w.shape[ax],), float(scales), np.float32)
        shape = [1] * w.ndim
        shape[ax] = w.shape[ax]
        q = np.clip(np.round(w / np.maximum(scales.reshape(shape), 1e-9) * qmax),
                    -qmax - 1, qmax).astype(np.int8)
        return Int8DeployedLinear(
            q, scales, bias=getattr(inner, "bias", None),
            act_scale=act_scale,
            act_bits=aq.bit_length() if aq is not None else 8, wt_bits=bits)
    if hasattr(nn, "Conv2D") and isinstance(inner, nn.Conv2D):
        if scales.ndim == 0:
            scales = np.full((w.shape[0],), float(scales), np.float32)
        q = np.clip(np.round(w / np.maximum(scales.reshape(-1, 1, 1, 1), 1e-9)
                             * qmax), -qmax - 1, qmax).astype(np.int8)
        cfg = {"stride": getattr(inner, "_stride", 1),
               "padding": getattr(inner, "_padding", 0),
               "dilation": getattr(inner, "_dilation", 1),
               "groups": getattr(inner, "_groups", 1)}
        return Int8DeployedConv2D(
            q, scales, getattr(inner, "bias", None), cfg,
            act_scale=act_scale,
            act_bits=aq.bit_length() if aq is not None else 8, wt_bits=bits)
    return None


def _convert_tree(model):
    n = 0
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, _QuantedWrapper):
            lowered = _lower_wrapper(sub)
            if lowered is not None:
                model._sub_layers[name] = lowered
                n += 1
                continue
        n += _convert_tree(sub)
    return n


class QAT:
    """Quantization-aware training driver (reference quantization/qat.py:24):
    quantize() swaps quantable layers for fake-quant wrappers; convert()
    lowers the trained wrappers to DEPLOYED int8 layers (int8 weights +
    trained per-channel scales + frozen activation quant) — jit.save of
    the result is the deployable int8 artifact."""

    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _swap_layers(model, self._config, lambda f: f)

    def convert(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        for sub in model.sublayers(True) if hasattr(model, "sublayers") else []:
            if isinstance(sub, (BaseQuanter, BaseObserver)):
                sub.eval()
        _convert_tree(model)
        model.eval()
        return model


class PTQ:
    """Post-training quantization driver (reference quantization/ptq.py:22):
    quantize() installs observers; after calibration forwards, convert()
    freezes scales and lowers to the same deployed int8 layers as QAT."""

    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _swap_layers(model, self._config, lambda f: f)

    def convert(self, model, inplace=True):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        for sub in model.sublayers(True) if hasattr(model, "sublayers") else []:
            if isinstance(sub, (BaseQuanter, BaseObserver)):
                sub.eval()
        _convert_tree(model)
        model.eval()
        return model
