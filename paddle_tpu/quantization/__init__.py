"""paddle.quantization equivalent (reference:
python/paddle/quantization/__init__.py — QuantConfig, BaseQuanter,
BaseObserver, quanter factory, QAT, PTQ; observers/abs_max.py,
quanters/abs_max.py).

TPU-first: fake-quantization is a pure jnp round-clip with a
straight-through estimator via jax.custom_vjp, so QAT steps stay fully
jit-compilable; observers accumulate ranges as host-side state between
compiled steps (the same split the reference makes between pass-collected
statistics and kernel compute)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu._core.tensor import Tensor
from paddle_tpu.nn.layer.layers import Layer

__all__ = [
    "QuantConfig", "BaseQuanter", "BaseObserver", "quanter", "QAT", "PTQ",
    "AbsMaxObserver", "FakeQuanterWithAbsMaxObserver", "QuantedLinear",
    "QuantedConv2D",
]


# straight-through fake quant -------------------------------------------------

@jax.custom_vjp
def _fake_quant(x, scale, qmax):
    s = jnp.maximum(scale, 1e-9)
    return jnp.clip(jnp.round(x / s * qmax), -qmax - 1, qmax) * s / qmax


def _fq_fwd(x, scale, qmax):
    return _fake_quant(x, scale, qmax), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # straight-through: pass gradient inside the clip range, zero outside
    mask = (jnp.abs(x) <= jnp.maximum(scale, 1e-9)).astype(g.dtype)
    return g * mask, None, None


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


class BaseObserver(Layer):
    """Collects tensor statistics to derive scales (reference
    quantization/base_observer.py:22)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


class BaseQuanter(BaseObserver):
    """Trainable/simulated quantizer applied during QAT (reference
    quantization/base_quanter.py:22)."""


class AbsMaxObserver(BaseObserver):
    """Running abs-max observer (reference
    quantization/observers/abs_max.py:30)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._max = 1e-9

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        self._max = max(self._max, float(jnp.max(jnp.abs(xv))))
        return x

    def scales(self):
        return Tensor(jnp.asarray(self._max, jnp.float32))


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT fake-quant with moving-average abs-max (reference
    quantization/quanters/abs_max.py:32)."""

    def __init__(self, moving_rate=0.9, quant_bits=8, dtype="float32", name=None):
        super().__init__(quant_bits)
        self._moving_rate = moving_rate
        self._scale = 1e-9

    def forward(self, x):
        xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        if self.training:
            cur = float(jax.lax.stop_gradient(jnp.max(jnp.abs(xv))))
            r = self._moving_rate
            self._scale = r * self._scale + (1 - r) * cur
        qmax = float(2 ** (self._quant_bits - 1) - 1)
        return Tensor(_fake_quant(xv, jnp.asarray(self._scale, xv.dtype), qmax))

    def scales(self):
        return Tensor(jnp.asarray(self._scale, jnp.float32))


class _QuanterFactory:
    """Partial-binding factory (reference quantization/factory.py:49)."""

    def __init__(self, cls, *args, **kwargs):
        self.cls = cls
        self.args = args
        self.kwargs = kwargs

    def _instance(self, layer=None):
        return self.cls(*self.args, **self.kwargs)


def quanter(cls_or_name, *args, **kwargs):
    """Decorator/factory helper (reference factory.py:76): returns a
    factory whose instances are created per quantified tensor."""
    if isinstance(cls_or_name, type):
        return _QuanterFactory(cls_or_name, *args, **kwargs)

    def wrap(cls):
        return cls

    return wrap


class QuantConfig:
    """Maps layers/types/names to (activation, weight) quanter factories
    (reference quantization/config.py:57)."""

    def __init__(self, activation=None, weight=None):
        self._global_act = activation
        self._global_wt = weight
        self._layer_cfg = {}  # id(layer) -> (act, wt)
        self._type_cfg = {}  # layer type -> (act, wt)
        self._name_cfg = {}  # layer full name -> (act, wt)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) else [layer_name]
        for n in names:
            self._name_cfg[n] = (activation, weight)

    def _lookup(self, layer, name):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        if name in self._name_cfg:
            return self._name_cfg[name]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        if self._global_act or self._global_wt:
            return (self._global_act, self._global_wt)
        return None


class _QuantedWrapper(Layer):
    """Wraps a layer with activation/weight quanters (reference
    quantization/wrapper.py ObserveWrapper + imperative quant layers)."""

    def __init__(self, layer, act_factory, wt_factory):
        super().__init__()
        self._inner = layer
        self.activation_quanter = act_factory._instance(layer) if act_factory else None
        self.weight_quanter = wt_factory._instance(layer) if wt_factory else None

    def forward(self, x, *args, **kwargs):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None and hasattr(self._inner, "weight"):
            orig = self._inner.weight
            q = self.weight_quanter(orig)
            try:
                self._inner.weight = q
                return self._inner(x, *args, **kwargs)
            finally:
                self._inner.weight = orig
        return self._inner(x, *args, **kwargs)


QuantedLinear = _QuantedWrapper
QuantedConv2D = _QuantedWrapper


def _swap_layers(model, config, factory_filter):
    from paddle_tpu import nn

    quantable = (nn.Linear, nn.Conv2D) if hasattr(nn, "Conv2D") else (nn.Linear,)
    for name, sub in list(model._sub_layers.items()):
        cfg = config._lookup(sub, name)
        if cfg is not None and isinstance(sub, quantable):
            act, wt = cfg
            model._sub_layers[name] = _QuantedWrapper(sub, factory_filter(act), factory_filter(wt))
        else:
            _swap_layers(sub, config, factory_filter)
    return model


class QAT:
    """Quantization-aware training driver (reference quantization/qat.py:24):
    quantize() swaps quantable layers for fake-quant wrappers."""

    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _swap_layers(model, self._config, lambda f: f)

    def convert(self, model, inplace=False):
        """Freeze observers into plain dequant-scale layers (keeps the fake
        quant path; deployment lowering happens at jit.save)."""
        for sub in model.sublayers(True) if hasattr(model, "sublayers") else []:
            if isinstance(sub, (BaseQuanter, BaseObserver)):
                sub.eval()
        return model


class PTQ:
    """Post-training quantization driver (reference quantization/ptq.py:22):
    quantize() installs observers; after calibration forwards, convert()
    freezes scales."""

    def __init__(self, config):
        self._config = config

    def quantize(self, model, inplace=False):
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        return _swap_layers(model, self._config, lambda f: f)

    def convert(self, model, inplace=True):
        for sub in model.sublayers(True) if hasattr(model, "sublayers") else []:
            if isinstance(sub, (BaseQuanter, BaseObserver)):
                sub.eval()
        return model
