"""Benchmark: LLaMA decoder pretrain throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no absolute numbers (BASELINE.md), so vs_baseline is
computed as achieved MFU divided by 0.45 — the typical Megatron-style MFU
Paddle/PaddleNLP reaches for LLaMA pretraining on A100 (the north-star is
"match Paddle-on-A100 tokens/sec/chip", which at equal MFU is the same
comparison up to the peak-FLOPs ratio). vs_baseline >= 1.0 means we use our
chip at least as efficiently as the reference uses its GPU.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import threading

    import jax

    # persistent XLA compile cache: repeated bench runs (driver re-runs,
    # round restarts on one box) skip the multi-minute first compile
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    # The remote-TPU (axon) tunnel can wedge, making backend init hang
    # forever; emit an explicit zero result instead of timing out silently.
    init_done = threading.Event()

    def _init_watchdog():
        if not init_done.wait(300):
            print(
                json.dumps(
                    {
                        "metric": "llama_pretrain_tokens_per_sec_per_chip",
                        "value": 0.0,
                        "unit": "tokens/s",
                        "vs_baseline": 0.0,
                        "error": "TPU backend init exceeded 300s (tunnel unreachable)",
                        "last_measured_on_chip": {
                            "date": "2026-07-30",
                            "hidden1024_config": {"tokens_per_sec": 88102.94, "vs_baseline": 1.1037},
                            "hidden2048_config_probe": {"tokens_per_sec": 35618.4, "mfu": 0.6245, "vs_baseline": 1.388},
                            "note": "last successful on-chip measurement (see date field); BASELINE.md has the full table",
                        },
                    }
                ),
                flush=True,
            )
            import os

            os._exit(3)

    threading.Thread(target=_init_watchdog, daemon=True).start()
    platform = jax.devices()[0].platform
    init_done.set()
    on_accel = platform not in ("cpu",)

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if on_accel:
        # hidden 2048 doubles the MXU tile occupancy vs 1024: measured 0.62
        # vs 0.50 MFU on the v5e (ablation in BASELINE.md round-2 notes)
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_hidden_layers=8,
            num_attention_heads=16,
            num_key_value_heads=16,
            max_position_embeddings=1024,
            dtype="bfloat16",
        )
        B, S, iters = 4, 1024, 10
    else:  # dev smoke on CPU
        cfg = LlamaConfig(
            vocab_size=1024,
            hidden_size=256,
            intermediate_size=688,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=4,
            max_position_embeddings=512,
            dtype="float32",
        )
        B, S, iters = 2, 128, 3

    # Build (param init) on the host CPU backend: eager per-op dispatch on a
    # remote-attached TPU pays one XLA compile round-trip per op.  The whole
    # hot path is the compiled TrainStep anyway; it pulls the state to the
    # accelerator on the first call.
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(), weight_decay=0.01)

    def loss_fn(m, ids, labels):
        loss, _ = m(ids, labels=labels)
        return loss

    step = TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, size=(B, S)).astype(np.int32))
    labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, size=(B, S)).astype(np.int64))

    step(ids, labels)  # builds optimizer state on host, compiles, runs
    step(ids, labels)._value.block_until_ready()

    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        loss = step(ids, labels)
    loss._value.block_until_ready()
    dt = time.perf_counter() - t0

    tokens_per_sec = B * S * iters / dt

    # achieved model FLOPs (6 * n_params per token, attention term included)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params + 12 * cfg.num_hidden_layers * cfg.hidden_size * S
    achieved_tflops = tokens_per_sec * flops_per_token / 1e12

    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind:
        peak = 197.0
    elif "v5p" in kind or "v5" in kind:
        peak = 459.0
    elif platform != "cpu":
        peak = 275.0  # v4 default
    else:
        peak = 0.0
    if peak:
        mfu = achieved_tflops / peak
        vs_baseline = mfu / 0.45
    else:
        vs_baseline = 0.0

    print(
        json.dumps(
            {
                "metric": "llama_pretrain_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 2),
                "unit": "tokens/s",
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
