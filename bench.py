"""Benchmark: LLaMA decoder pretrain throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no absolute numbers (BASELINE.md), so vs_baseline is
computed as achieved MFU divided by 0.45 — the typical Megatron-style MFU
Paddle/PaddleNLP reaches for LLaMA pretraining on A100 (the north-star is
"match Paddle-on-A100 tokens/sec/chip", which at equal MFU is the same
comparison up to the peak-FLOPs ratio). vs_baseline >= 1.0 means we use our
chip at least as efficiently as the reference uses its GPU.

Tunnel-flap hardening: the remote-TPU (axon) backend init can wedge forever.
The parent process first runs cheap device probes in subprocesses with a
bounded timeout and exponential backoff; only after a probe succeeds does it
launch the measurement child (whose XLA compiles hit the persistent cache, so
a retry does not pay the full compile again).  All failures emit a clean
zero-value JSON line — no stale historical numbers in the payload
(see BASELINE.md for history).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

METRIC = "llama_pretrain_tokens_per_sec_per_chip"
CACHE_DIR = "/tmp/jax_cache"

PROBE_TIMEOUT = 90  # seconds per probe attempt (first TPU init ~20-40s)
PROBE_BACKOFFS = (10, 20, 40)  # sleep between probe attempts
BENCH_TIMEOUT = 900  # full measurement incl. cold compile
BENCH_ATTEMPTS = 2


def _fail(error: str, code: int = 3) -> int:
    print(
        json.dumps(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": "tokens/s",
                "vs_baseline": 0.0,
                "error": error,
            }
        ),
        flush=True,
    )
    return code


def _probe() -> bool:
    """Initialize the jax backend in a throwaway subprocess, bounded."""
    code = (
        "import jax, os; "
        "os.environ.get('PADDLE_TPU_BENCH_CPU') and jax.config.update('jax_platforms', 'cpu'); "
        "jax.config.update('jax_compilation_cache_dir', %r); "
        "d = jax.devices(); print('PROBE_OK', d[0].platform, flush=True)" % CACHE_DIR
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            timeout=PROBE_TIMEOUT,
            capture_output=True,
            text=True,
        )
        return out.returncode == 0 and "PROBE_OK" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def parent() -> int:
    ok = _probe()
    for backoff in PROBE_BACKOFFS:
        if ok:
            break
        time.sleep(backoff)
        ok = _probe()
    if not ok:
        return _fail(
            "TPU backend init failed %d probe attempts (tunnel unreachable); "
            "see BASELINE.md for the last recorded on-chip measurement"
            % (1 + len(PROBE_BACKOFFS))
        )

    env = dict(os.environ, PADDLE_TPU_BENCH_CHILD="1")
    last_err = "unknown"
    for attempt in range(BENCH_ATTEMPTS):
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                timeout=BENCH_TIMEOUT,
                capture_output=True,
                text=True,
                env=env,
            )
        except subprocess.TimeoutExpired:
            last_err = "measurement child exceeded %ds" % BENCH_TIMEOUT
            continue
        line = next(
            (
                ln
                for ln in reversed(out.stdout.splitlines())
                if ln.startswith("{") and '"metric"' in ln
            ),
            None,
        )
        if out.returncode == 0 and line:
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                last_err = "child emitted unparseable JSON"
                continue
            if parsed.get("value", 0) > 0:
                print(line, flush=True)
                return 0
            last_err = parsed.get("error", "child reported zero value")
        else:
            last_err = "child rc=%d: %s" % (
                out.returncode,
                (out.stderr or out.stdout).strip().splitlines()[-1:]
                or ["no output"],
            )
        if attempt + 1 < BENCH_ATTEMPTS and not _probe():
            time.sleep(30)
    return _fail("measurement failed after %d attempts: %s" % (BENCH_ATTEMPTS, last_err))


def _pipeline_detail(S: int = 4, M: int = 16) -> dict:
    """Simulator-backed pipeline-schedule section (ROADMAP item 3): bubble
    fraction per registered schedule at the flagship (S, M), pure host math
    from fleet/meta_parallel/schedules.py — CPU-falsifiable, rides every
    payload so tools/check_bench_regression.py can gate bubble growth
    (lower is better) the moment a schedule table changes."""
    from paddle_tpu.distributed.fleet.meta_parallel import schedules as sched

    out = {"S": S, "M": M, "schedules": {}, "peak_residency": {}}
    for name in sched.available_schedules():
        r = sched.simulate(name, S, M)
        out["schedules"][name] = round(r.bubble_fraction, 6)
        out["peak_residency"][name] = r.peak_residency
    return out


def child(smoke: bool = False) -> int:
    import numpy as np
    import jax

    if os.environ.get("PADDLE_TPU_BENCH_CPU"):  # dev smoke without the tunnel
        jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    platform = jax.devices()[0].platform
    on_accel = platform not in ("cpu",)

    import paddle_tpu as paddle
    from paddle_tpu.device import hard_sync
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if on_accel:
        # Flagship config: hidden 2048 doubles the MXU tile occupancy vs
        # 1024 — measured 0.62 vs 0.50 MFU on the v5e (BASELINE.md round-2).
        cfg = LlamaConfig(
            vocab_size=32000,
            hidden_size=2048,
            intermediate_size=5632,
            num_hidden_layers=8,
            num_attention_heads=16,
            num_key_value_heads=16,
            max_position_embeddings=1024,
            dtype="bfloat16",
        )
        B, S, iters = 4, 1024, 10
    else:  # dev smoke on CPU
        cfg = LlamaConfig(
            vocab_size=1024,
            hidden_size=256,
            intermediate_size=688,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=4,
            max_position_embeddings=512,
            dtype="float32",
        )
        B, S, iters = 2, 128, 3

    # Build (param init) on the host CPU backend: eager per-op dispatch on a
    # remote-attached TPU pays one XLA compile round-trip per op.  The whole
    # hot path is the compiled TrainStep anyway; it pulls the state to the
    # accelerator on the first call.
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters(), weight_decay=0.01)

    def loss_fn(m, ids, labels):
        loss, _ = m(ids, labels=labels)
        return loss

    step = TrainStep(model, opt, loss_fn)
    rng = np.random.default_rng(0)

    from paddle_tpu.device import time_step_ms

    def measure(batch):
        ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size, size=(batch, S)).astype(np.int32))
        labels = paddle.to_tensor(rng.integers(0, cfg.vocab_size, size=(batch, S)).astype(np.int64))
        step(ids, labels)  # builds optimizer state on host, compiles, runs
        hard_sync(step(ids, labels))
        ms = time_step_ms(lambda: step(ids, labels), inner=iters)
        return batch * S / (ms / 1e3)

    # per-config MFU (ROADMAP item 3: the gain must be visible per swept
    # config the moment the tunnel returns, not just for the winner)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params + 12 * cfg.num_hidden_layers * cfg.hidden_size * S

    from paddle_tpu.device.peaks import device_peak_tflops

    kind = jax.devices()[0].device_kind.lower()
    peak = device_peak_tflops(kind, platform)

    def _mfu(tps: float) -> float:
        return (tps * flops_per_token / 1e12) / peak if peak else 0.0

    configs = []
    if on_accel:
        # batch sweep, largest first: bigger batches fill the MXU better
        # until HBM runs out — an OOM falls through to the next size
        tokens_per_sec, best_b = 0.0, B
        for batch in (16, 8, 4):
            try:
                tps = measure(batch)
            except Exception as e:  # noqa: BLE001
                msg = f"{type(e).__name__}: {e}"
                print(f"bench: B={batch} failed ({msg[:200]})", file=sys.stderr)
                if "RESOURCE_EXHAUSTED" not in msg and "Out of memory" not in msg:
                    raise
                continue
            configs.append({"config": f"hidden2048_L8_bf16_B{batch}",
                            "tokens_per_sec": round(tps, 2),
                            "mfu": round(_mfu(tps), 4)})
            if tps > tokens_per_sec:
                tokens_per_sec, best_b = tps, batch
        B = best_b
        if tokens_per_sec == 0.0:
            # every batch OOMed: an error payload (not a zero-value
            # success line) so the parent reports the real cause instead
            # of burning cold-compile retries on a deterministic failure
            return _fail("all sweep batch sizes hit device OOM")
    else:
        tokens_per_sec = measure(B)
        configs.append({"config": "cpu_smoke",
                        "tokens_per_sec": round(tokens_per_sec, 2),
                        "mfu": round(_mfu(tokens_per_sec), 4)})

    mfu = _mfu(tokens_per_sec)
    vs_baseline = mfu / 0.45 if peak else 0.0

    payload = {
        "metric": METRIC,
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 4),
        "mfu": round(mfu, 4),
        "device_kind": kind,
        "config": (f"hidden2048_L8_bf16_B{B}" if on_accel else "cpu_smoke"),
        "configs": configs,
        "detail": {"pipeline": _pipeline_detail()},
    }
    print(json.dumps(payload), flush=True)
    if smoke:
        _assert_smoke(payload)
        print("BENCH_SMOKE_OK", flush=True)
    return 0


def _assert_smoke(payload: dict):
    """--smoke contract: the CPU twin proves the payload SHAPE the on-chip
    run will carry — per-config mfu fields and the simulator-backed
    pipeline section with ZB-H1 strictly under 1F1B — so a field
    regression fails in CI, not in the first post-tunnel round."""
    assert payload["value"] > 0, payload
    assert payload["configs"], "configs sweep section missing"
    for c in payload["configs"]:
        assert "mfu" in c and "tokens_per_sec" in c and "config" in c, c
    pl = payload["detail"]["pipeline"]
    scheds = pl["schedules"]
    for name in ("FThenB", "1F1B", "ZB-H1"):
        assert name in scheds, f"{name} missing from pipeline section"
    assert scheds["ZB-H1"] < scheds["1F1B"], scheds
    assert pl["peak_residency"]["ZB-H1"] <= pl["peak_residency"]["1F1B"], pl


if __name__ == "__main__":
    if "--smoke" in sys.argv:
        # CPU twin (no tunnel, no probe tier): measure the smoke config
        # in-process and assert the payload contract
        os.environ["PADDLE_TPU_BENCH_CPU"] = "1"
        sys.exit(child(smoke=True))
    if os.environ.get("PADDLE_TPU_BENCH_CHILD"):
        sys.exit(child())
    sys.exit(parent())
